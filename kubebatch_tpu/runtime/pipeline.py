"""Pipelined scheduling cycles — the blocking readback off the critical
path (ISSUE 16; ROADMAP item 1's latency lever).

The sequential loop is strictly ordered per cycle: fold/tensorize ->
one device dispatch -> one BLOCKING readback -> host apply/bind.
Through the axon tunnel the readback pays the full link RTT (~75 ms,
BENCH_NOTES), so cycle latency has a hard floor no kernel speedup can
cross. This executor restructures the loop so the readback of cycle
N's solve overlaps cycle N+1's host work:

cycle N:   consume N-1's in-flight result (conflict-check, replay) ->
           run actions; allocate TENSORIZES and DISPATCHES the solve
           for N's pending set, then returns without reading it back ->
           close the session (adoption hands N's clones to N+1's base)
cycle N+1: the solve result lands while N+1's open/fold/pack runs;
           consume pays a DEFERRED readback (usually already on the
           host — ``copy_to_host_async`` started the transfer at
           dispatch) and replays N's decisions into N+1's session.

Why replaying a cycle late is sound — the rebase argument
(docs/INCREMENTAL.md "Pipelined cycles"): session N closed WITHOUT
applying the in-flight decisions, so cache truth never saw them and
session N+1's snapshot still carries every placed task as pending.
The tensorized inputs, though, hold session N's clones — OpenSession
re-clones, so N+1 holds different instances for the same uids.
Consume therefore REBASES the inputs' job/task references onto
session N+1's objects by uid (cycle_inputs.rebase_inputs) before
replaying; the replay then performs precisely the mutations session N
would have performed, one cycle later, and the bind write-back lands
in cache truth exactly once. A placed uid that no longer resolves as
pending is staleness the conflict fingerprint missed — the rebase
fails and the cycle invalidates like any other conflict.

Optimism and its guard rails: while a solve is in flight, cache events
keep folding. The fold layer tags every mark into a flight window
(EventFold.begin_flight/end_flight); at consume time the executor
checks whether any flight-marked job/node intersects the in-flight
decisions' footprint. Our OWN committed binds echo back through the
kubelet (a Running flip re-marks the job and node we just bound), so
the check subtracts the footprint of the last two commits — EXCEPT for
node-shape/capacity marks (``flight_caps``), which are never our echo
and always conflict. A conflict (or the armed ``pipeline.conflict``
seam) invalidates: the decisions are discarded untouched (nothing was
replayed, so there is nothing to roll back), the device carry restores
from its pre-dispatch shadow, and the CURRENT cycle runs the
sequential path — the conflicted tasks are still pending in this very
session, so "re-solve against the fresh active set" is just the
ordinary solve. Repeated conflicts (the storm) demote the executor to
the sequential loop for the rest of the process — the same sticky
demote-not-raise rung as cache.fold and solve.activeset.

The executor only engages the active-set/hier engine family (the
engines with a persistent device carry and a packed result frame);
every other mode, a ladder-degraded process, affinity cycles, and
declined solves run the ordinary ``AllocateAction.execute`` path
unchanged.
"""
from __future__ import annotations

import logging
from collections import deque
from typing import Optional

import numpy as np

from .. import obs as _obs
from ..faults import armed as _faults_armed
from ..faults import should_fail as _should_fail
from ..framework import CloseSession, OpenSession
from ..metrics import (count_pipeline_conflict, count_pipeline_cycle,
                       count_pipeline_demotion)

log = logging.getLogger("kubebatch.pipeline")

#: consecutive consume-time conflicts that demote the executor — at
#: this rate the overlap re-solves more cycles than it saves
CONFLICT_STORM_LIMIT = 3

#: how many past commits' footprints the echo window remembers; the
#: kubelet Running flip for a bind normally echoes within one cycle,
#: two covers a slow tick without letting real staleness hide long
ECHO_WINDOW = 2

_demoted = False


def demoted() -> bool:
    return _demoted


def demote(reason: str) -> None:
    """The sticky rung back to the sequential loop: conflict storms (or
    anything else that makes the overlap a net loss) land here — never
    an exception into the scheduling loop. Idempotent; restart (or
    reset(), tests) to re-enable."""
    global _demoted
    if _demoted:
        return
    _demoted = True
    count_pipeline_demotion(reason)
    log.error("pipelined executor DEMOTED to the sequential loop "
              "(reason=%s): cycles run fold -> dispatch -> blocking "
              "readback -> apply again; restart to re-enable", reason)
    try:
        from ..obs import flight as _flight
        _flight.dump(f"pipeline_demotion-{reason}")
    except Exception:             # pragma: no cover — observer bug
        log.exception("pipeline demotion flight dump failed")


def reset() -> None:
    """Test/bench hook: forget the demotion."""
    global _demoted
    _demoted = False


class PendingCycle:
    """One in-flight solve: the kernel-side future plus everything the
    NEXT cycle needs to consume it — the tensorized inputs it will
    replay through and the launching cycle's epoch tag for the obs
    tree."""

    __slots__ = ("solve", "inputs", "epoch")

    def __init__(self, solve, inputs, epoch):
        self.solve = solve
        self.inputs = inputs
        self.epoch = epoch


class PipelinedExecutor:
    """Drives one scheduler's cycles in pipelined form. Owned by
    Scheduler (constructed when ``pipeline=True``); run_once here
    replaces Scheduler.run_once's session block while the executor is
    active. All state is per-scheduler except the process-wide demotion
    flag above."""

    def __init__(self, scheduler):
        self.sched = scheduler
        self._pending: Optional[PendingCycle] = None
        #: footprints (jobs, nodes) of the last ECHO_WINDOW commits —
        #: subtracted from the flight marks so our own bind echo never
        #: reads as a conflict
        self._echo: deque = deque(maxlen=ECHO_WINDOW)
        self._streak = 0

    # ------------------------------------------------------------------
    def active(self) -> bool:
        return not _demoted

    def reset(self) -> None:
        """Drop in-flight state AND the module demotion (tests/bench)."""
        self._pending = None
        self._echo.clear()
        self._streak = 0
        reset()

    # ------------------------------------------------------------------
    def run_once(self, snapshot=None) -> None:
        """One pipelined cycle: Scheduler.run_once's session block with
        (a) the previous cycle's in-flight result consumed FIRST —
        before any action sees the session — and (b) the allocate
        action routed through the async-dispatch path."""
        sched = self.sched
        jobs = nodes = None
        session_span = None
        try:
            with _obs.span("session", cat="e2e") as session_span:
                ssn = OpenSession(sched.cache, sched.tiers,
                                  sched.enable_preemption,
                                  snapshot=snapshot)
                jobs, nodes = len(ssn.jobs), len(ssn.nodes)
                try:
                    sequential = self._consume(ssn)
                    for action in sched.actions:
                        action.initialize()
                        with _obs.span(action.name, cat="action") as asp:
                            if action.name == "allocate":
                                self._allocate(ssn, action, sequential)
                            else:
                                action.execute(ssn)
                        log.debug("action %s took %.2fms", action.name,
                                  1e3 * asp.dur)
                        action.uninitialize()
                    if sched.explain_unschedulable:
                        from ..obs import explain as _explain
                        try:
                            with _obs.span("explain", cat="host"):
                                _explain.explain_session(ssn)
                        except Exception:
                            log.exception("unschedulability explainer "
                                          "failed; cycle unaffected")
                finally:
                    CloseSession(ssn)
        finally:
            if jobs is not None:
                log.info("scheduling cycle: %d jobs / %d nodes in %.2fms "
                         "(pipelined)", jobs, nodes,
                         1e3 * session_span.dur)

    def drain(self, max_cycles: int = 3) -> None:
        """Flush the in-flight solve by running whole cycles until the
        pipeline is empty (an empty pending set dispatches nothing, so
        one cycle normally suffices). Benches and tests call this so
        every dispatched decision has been applied before they compare
        state; the chaos quiesce loop gets the same effect from its
        settle cycles."""
        n = 0
        while self._pending is not None and n < max_cycles:
            self.sched.run_cycle()
            n += 1

    # ------------------------------------------------------------------
    # consume side
    # ------------------------------------------------------------------
    def _consume(self, ssn) -> bool:
        """Consume the previous cycle's in-flight result into ``ssn``.
        Returns True when the result was invalidated — the caller then
        runs THIS cycle sequentially (its session still carries the
        conflicted tasks as pending, so the ordinary solve IS the
        re-solve against the fresh active set)."""
        from ..actions.cycle_inputs import rebase_inputs, replay_decisions

        pend, self._pending = self._pending, None
        if pend is None:
            return False
        fold = getattr(self.sched.cache, "fold", None)
        flight = (fold.end_flight() if fold is not None
                  else (set(), set(), set()))
        with _obs.span("consume", cat="host", epoch=pend.epoch) as sp:
            task_state, task_node, task_seq, _ = pend.solve.consume(sp)
        fp_jobs, fp_nodes = self._footprint(pend.inputs, task_state,
                                            task_node)
        outcome = None
        if _faults_armed() and _should_fail("pipeline.conflict"):
            outcome = "fault"
        elif self._is_conflict(fp_jobs, fp_nodes, flight):
            outcome = "conflict"
        elif not rebase_inputs(ssn, pend.inputs, task_state):
            # a placed task no longer resolves as pending in this
            # session — staleness the fingerprint missed (echo-masked)
            outcome = "conflict"
        if outcome is None:
            count_pipeline_cycle()
            # ledger: binds replayed here consume epoch k's solve inside
            # cycle k+1 — attribute fold/pack/solve to the LAUNCHING
            # epoch and flag the records deferred (the invalidated path
            # below replays nothing, so it closes nothing: the
            # sequential re-solve closes those records normally)
            from ..obs import ledger as _ledger
            with _ledger.attribute(pend.epoch, deferred=True):
                replay_decisions(ssn, pend.inputs, task_state, task_node,
                                 task_seq)
            self._echo.append((fp_jobs, fp_nodes))
            self._streak = 0
            return False
        # stale: discard untouched (nothing was replayed, so cache
        # truth and the session never saw these decisions), roll the
        # device carry back to its pre-dispatch shadow, and let this
        # cycle solve sequentially
        count_pipeline_conflict(outcome)
        pend.solve.restore_carry()
        self._echo.clear()
        self._streak += 1
        log.warning("pipelined result invalidated at consume "
                    "(%s; streak %d/%d): %d jobs / %d nodes in the "
                    "flight window touched the decision footprint — "
                    "re-solving this cycle sequentially", outcome,
                    self._streak, CONFLICT_STORM_LIMIT,
                    len(flight[0]), len(flight[1]))
        if self._streak >= CONFLICT_STORM_LIMIT:
            demote("storm")
        return True

    @staticmethod
    def _footprint(inputs, task_state, task_node):
        """(job uids, node names) the in-flight decisions bind against."""
        from ..kernels.fused import ALLOC, ALLOC_OB, PIPELINE

        n = len(inputs.tasks)
        state = np.asarray(task_state)[:n]
        placed = np.nonzero((state == ALLOC) | (state == ALLOC_OB)
                            | (state == PIPELINE))[0]
        names = inputs.device.state.names
        node_cols = np.asarray(task_node)[:n][placed]
        fp_jobs = {inputs.tasks[int(i)].job for i in placed.tolist()}
        fp_nodes = {names[int(c)] for c in node_cols.tolist()
                    if 0 <= int(c) < len(names)}
        return fp_jobs, fp_nodes

    def _is_conflict(self, fp_jobs, fp_nodes, flight) -> bool:
        """Did any event folded while the solve was in flight touch an
        entity the decisions bind against? Capacity marks always
        conflict; plain job/node marks are screened against the echo of
        our own recent commits (the kubelet Running flip for a bind we
        made re-marks exactly the footprint we recorded)."""
        flight_jobs, flight_nodes, flight_caps = flight
        if flight_caps & fp_nodes:
            return True
        echo_jobs: set = set()
        echo_nodes: set = set()
        for ej, en in self._echo:
            echo_jobs |= ej
            echo_nodes |= en
        return bool(((flight_jobs - echo_jobs) & fp_jobs)
                    or ((flight_nodes - echo_nodes) & fp_nodes))

    # ------------------------------------------------------------------
    # dispatch side
    # ------------------------------------------------------------------
    def _allocate(self, ssn, action, sequential: bool) -> None:
        """The allocate action with the async-dispatch path: when the
        active-set engine may claim this cycle, tensorize + dispatch
        and return with the result in flight; otherwise (other engine
        families, ladder-degraded process, declined solve, or a
        conflict this cycle) run the ordinary sequential execute."""
        from ..actions import allocate as _alloc
        from ..actions.allocate_batched import batched_supported
        from ..actions.cycle_inputs import EMPTY_CYCLE, build_cycle_inputs
        from ..faults import check as _fault_check
        from ..kernels import activeset as _activeset

        mode = action.mode
        eff = action._auto_mode(ssn) if mode == "auto" else mode
        pipelinable = (not sequential
                       and eff in ("hier", "activeset")
                       and (eff == "activeset" or mode == "auto")
                       and self.sched.ladder.level == 0
                       and not _activeset.demoted()
                       and batched_supported(ssn))
        if not pipelinable:
            action.execute(ssn)
            return
        inputs = build_cycle_inputs(ssn, allow_affinity=True)
        if inputs is EMPTY_CYCLE:
            _alloc.last_cycle_engine = "hier"
            return
        if inputs is None or getattr(inputs, "affinity", None) is not None:
            self._sequential(ssn, action, eff, inputs)
            return
        # same seam the sequential path crosses before its dispatch
        _fault_check("device.dispatch")
        pend = _activeset.solve_cycle_async(inputs.device, inputs)
        if pend is None:
            # the engine declined (cold-sized set, inexact pairs,
            # demoted): run this cycle on the full-width path, reusing
            # the inputs already built
            self._sequential(ssn, action, eff, inputs)
            return
        _alloc.last_cycle_engine = "activeset"
        fold = getattr(self.sched.cache, "fold", None)
        if fold is not None:
            fold.begin_flight()
        self._pending = PendingCycle(pend, inputs, _obs.current_epoch())

    @staticmethod
    def _sequential(ssn, action, eff: str, inputs) -> None:
        """The sequential fallback AFTER inputs were built: the one-shot
        active-row state is already consumed, so re-entering
        action.execute (which rebuilds inputs) would hand the solve an
        empty active set — route through execute_batched with the
        prebuilt inputs instead, mirroring AllocateAction.execute's
        fallback chain past it."""
        from ..actions import allocate as _alloc
        from ..actions.allocate_batched import execute_batched
        from ..metrics import count_engine_demotion

        ran = execute_batched(ssn, hier=True, activeset=True,
                              inputs=inputs)
        if ran:
            _alloc.last_cycle_engine = ran
            return
        count_engine_demotion(eff, "visit")
        action._execute_queued(ssn, "batched")
