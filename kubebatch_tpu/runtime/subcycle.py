"""Schedule-on-arrival sub-cycle — latency-lane pods don't wait for t.

The period loop solves the whole cluster once per ``schedule_period``
(1 s by default); a latency-sensitive pod that arrives right after a
cycle closes used to wait the full period for its placement. With the
event-fold layer the cache carries everything a solve needs ACROSS
cycles — the folded host base and the persistent device arrays — so a
narrow allocate can run the moment the pod lands:

- the cache's arrival hook fires (outside the cache lock) for every
  PENDING pod whose lane annotation says ``latency``;
- the scheduler drains queued arrivals under its cycle lock (a sub-cycle
  never overlaps a full cycle; bursts coalesce into one sub-cycle);
- the sub-cycle opens a session off the folded snapshot (O(events)),
  re-packs only the dirty device rows, and runs ONE per-visit allocate
  scan for the arrived pod's job — one dispatch, one blocking readback,
  through the SAME registered compilesvc shape buckets the period loop
  warmed (a 1-pod gang pads to the smallest registered gang bucket), so
  recompiles stay 0;
- decisions apply through the ordinary Session mutators and CloseSession
  write-back, which is the whole idempotence argument: the bind lands in
  cache truth as BINDING, the session clones are adopted as the next
  base, and the next FULL cycle sees a non-pending task — it re-places
  nothing, exactly as if the bind had happened in a previous full cycle
  (docs/INCREMENTAL.md "sub-cycle idempotence").

Each sub-cycle runs under its own obs cycle root (name "subcycle"), so
it shows up as a separate root in Chrome traces and the flight ring;
arrival -> decision latency streams into the decision ledger
(obs/ledger.py; ``subcycle_arrival`` percentiles on /debug/vars — the
raw-list ``metrics.ARRIVAL_STATS`` reservoir is deprecated).
"""
from __future__ import annotations

import logging
import time
from typing import List, Tuple

from .. import obs as _obs
from ..api import TaskStatus
from ..api.job import get_job_id
from ..metrics import count_subcycle, observe_arrival_latency
from ..objects import Pod

log = logging.getLogger("kubebatch.subcycle")

#: pod annotation carrying the service lane — same vocabulary as the
#: tenantsvc rpc lanes (kb-lane metadata: latency > normal > batch).
#: Single-sourced in obs/ledger.py (the ledger keys histograms by lane);
#: re-exported here for the existing import sites
from ..obs.ledger import (DEFAULT_LANE, LANE_ANNOTATION,  # noqa: E402
                          LATENCY_LANE)


def pod_lane(pod: Pod) -> str:
    return pod.annotations.get(LANE_ANNOTATION, DEFAULT_LANE)


def is_latency_pod(pod: Pod) -> bool:
    """True for pods the sub-cycle serves: PENDING arrivals on the
    latency lane."""
    return pod_lane(pod) == LATENCY_LANE


def _job_uid(pod: Pod) -> str:
    """The cache's job uid for this pod (grouped pods: 'ns/group';
    ungrouped pods get the shadow-group uid, cache/cache.py
    create_shadow_pod_group)."""
    return get_job_id(pod) or str(pod.owner_uid or pod.uid)


def run_subcycle(scheduler, arrivals: List[Tuple[Pod, float]]) -> int:
    """One narrow allocate for ``arrivals`` (a list of (pod, t_arrival)
    perf_counter pairs). Returns the number of arrived pods that got a
    decision. Caller (Scheduler._drain_arrivals) holds the cycle lock
    and guards exceptions — a failing sub-cycle is logged and counted
    (cycle_failures{reason=subcycle}), never propagated into the event
    pump."""
    from ..framework import CloseSession, OpenSession

    cache = scheduler.cache
    scheduler._subcycle_seq += 1
    root = _obs.begin_cycle(scheduler._subcycle_seq, name="subcycle",
                            arrivals=len(arrivals))
    decided = 0
    try:
        with _obs.span("subcycle", cat="phase"):
            ssn = OpenSession(cache, scheduler.tiers,
                              scheduler.enable_preemption)
            try:
                decided = _solve_arrivals(ssn, arrivals)
            finally:
                CloseSession(ssn)
    finally:
        _obs.end_cycle(root)
    count_subcycle()
    return decided


def _solve_arrivals(ssn, arrivals: List[Tuple[Pod, float]]) -> int:
    """The narrow allocate: one per-visit solve per arrived job against
    the live device arrays (or the reference host loop when the session
    carries features outside the device vocabulary — same gate as the
    period loop's per-visit path)."""
    from ..actions.allocate import AllocateAction
    from ..kernels.solver import ensure_device_snapshot
    from ..kernels.terms import device_supported, solver_terms
    from ..util import PriorityQueue

    #: job uid -> [(pod, t_arrival)] — a burst of same-gang arrivals
    #: solves in one visit
    by_job = {}
    for pod, t0 in arrivals:
        by_job.setdefault(_job_uid(pod), []).append((pod, t0))

    act = AllocateAction(mode="jax")
    device = None
    terms = None
    pending = [t for uid in by_job
               for j in (ssn.jobs.get(uid),) if j is not None
               for t in j.task_status_index.get(TaskStatus.PENDING,
                                                {}).values()
               if not t.resreq.is_empty()]
    if pending and device_supported(ssn, pending):
        device = ensure_device_snapshot(ssn)
        terms = solver_terms(ssn, device, pending, assume_supported=True)
        if terms is None:
            device = None

    decided = 0
    for uid, pods in by_job.items():
        job = ssn.jobs.get(uid)
        if job is None:
            continue
        tasks = PriorityQueue(ssn.task_order_fn)
        for task in job.task_status_index.get(TaskStatus.PENDING,
                                              {}).values():
            if not task.resreq.is_empty():
                tasks.push(task)
        if tasks.empty():
            continue
        jobs_pq = PriorityQueue(ssn.job_order_fn)   # one visit; re-push
        #                                             goes nowhere
        if device is not None:
            act._visit_job_device(ssn, device, job, tasks, jobs_pq, terms)
        else:
            act._visit_job_host(ssn, job, tasks, jobs_pq)
        if not ssn.job_ready(job):
            # gang barrier: a lone member of a min_member > 1 gang may
            # sit ALLOCATED in the session, but the all-or-nothing gate
            # discards that at close — the pod was NOT decided, it
            # waits for the rest of its gang (then the period loop)
            continue
        now = time.perf_counter()
        for pod, t0 in pods:
            task = job.tasks.get(pod.uid)
            if task is not None and task.status != TaskStatus.PENDING:
                # the pod got a decision (ALLOCATED / BINDING /
                # PIPELINED) this sub-cycle AND its gang is at quorum,
                # so the close write-back dispatches it: that IS the
                # arrival -> decision latency the lane promises
                observe_arrival_latency(max(0.0, now - t0))
                decided += 1
    return decided
