"""kubebatch_tpu — a TPU-native batch/gang scheduling framework.

A from-scratch re-design of kube-batch's capability set (reference:
DonghuiZhuo/kube-batch-1) where the O(pods x nodes) predicate / scoring /
bin-packing hot loops of each scheduling cycle run as dense JAX/XLA kernels
on TPU, while the session / action / plugin policy architecture remains a
thin host-side orchestration layer.

Layering (mirrors the reference's *capabilities*, not its class layout —
see SURVEY.md sect. 7):

- ``objects``   — cluster API objects (Pod/Node/PodGroup/Queue/...), the
                  equivalent of the reference's CRD + core-v1 types.
- ``api``       — in-memory domain model (Resource/TaskInfo/JobInfo/
                  NodeInfo/QueueInfo/ClusterInfo), ref pkg/scheduler/api.
- ``cache``     — cluster-state mirror + event ingestion + writeback seams,
                  ref pkg/scheduler/cache.
- ``framework`` — Session / plugin registry / tiered dispatch / Statement,
                  ref pkg/scheduler/framework.
- ``actions``   — allocate, backfill, preempt, reclaim policies,
                  ref pkg/scheduler/actions.
- ``plugins``   — gang, drf, proportion, priority, predicates, nodeorder,
                  conformance, ref pkg/scheduler/plugins.
- ``kernels``   — the TPU-native part with no reference counterpart: dense
                  tensorization of snapshots and jitted predicate-mask /
                  node-score / capacity-carrying assignment solvers
                  (vmap / lax.scan / shard_map over a device mesh).
- ``runtime``   — scheduler loop, YAML policy config, metrics, CLI,
                  ref pkg/scheduler/scheduler.go + cmd/kube-batch.
- ``sim``       — synthetic cluster generation and simulated e2e harness,
                  ref test/e2e's role (no real k8s needed).
"""

__version__ = "0.4.1"


def enable_persistent_compile_cache(path=None) -> str:
    """Point XLA's persistent compilation cache at the compile manager's
    managed, version-salted directory (compilesvc/cache.py — the
    subsystem that owns compile-state discipline; see docs/COMPILE.md).
    Process entry points (CLI, bench, tools/precompile.py) call this;
    embedders opt in explicitly. Set ``KUBEBATCH_COMPILE_CACHE=0`` to
    disable. Returns the directory ("" when disabled)."""
    from .compilesvc.cache import enable_persistent_compile_cache as enable

    return enable(path)
