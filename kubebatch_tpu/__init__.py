"""kubebatch_tpu — a TPU-native batch/gang scheduling framework.

A from-scratch re-design of kube-batch's capability set (reference:
DonghuiZhuo/kube-batch-1) where the O(pods x nodes) predicate / scoring /
bin-packing hot loops of each scheduling cycle run as dense JAX/XLA kernels
on TPU, while the session / action / plugin policy architecture remains a
thin host-side orchestration layer.

Layering (mirrors the reference's *capabilities*, not its class layout —
see SURVEY.md sect. 7):

- ``objects``   — cluster API objects (Pod/Node/PodGroup/Queue/...), the
                  equivalent of the reference's CRD + core-v1 types.
- ``api``       — in-memory domain model (Resource/TaskInfo/JobInfo/
                  NodeInfo/QueueInfo/ClusterInfo), ref pkg/scheduler/api.
- ``cache``     — cluster-state mirror + event ingestion + writeback seams,
                  ref pkg/scheduler/cache.
- ``framework`` — Session / plugin registry / tiered dispatch / Statement,
                  ref pkg/scheduler/framework.
- ``actions``   — allocate, backfill, preempt, reclaim policies,
                  ref pkg/scheduler/actions.
- ``plugins``   — gang, drf, proportion, priority, predicates, nodeorder,
                  conformance, ref pkg/scheduler/plugins.
- ``kernels``   — the TPU-native part with no reference counterpart: dense
                  tensorization of snapshots and jitted predicate-mask /
                  node-score / capacity-carrying assignment solvers
                  (vmap / lax.scan / shard_map over a device mesh).
- ``runtime``   — scheduler loop, YAML policy config, metrics, CLI,
                  ref pkg/scheduler/scheduler.go + cmd/kube-batch.
- ``sim``       — synthetic cluster generation and simulated e2e harness,
                  ref test/e2e's role (no real k8s needed).
"""

__version__ = "0.4.1"


def enable_persistent_compile_cache(path=None) -> str:
    """Point XLA's persistent compilation cache at ``path`` (default
    ``$KUBEBATCH_COMPILE_CACHE`` or ``~/.cache/kubebatch-tpu/xla``) so a
    restarted scheduler reuses compiled solver programs instead of
    re-tracing+compiling them — measured on the v5e tunnel, the first
    cfg5 solve of a fresh process drops 67 s -> 11 s. Process entry
    points (CLI, bench) call this; embedders opt in explicitly. Set
    ``KUBEBATCH_COMPILE_CACHE=0`` to disable. Returns the directory
    ("" when disabled)."""
    import os

    env = os.environ.get("KUBEBATCH_COMPILE_CACHE", "")
    if env in ("0", "false", "off"):
        return ""
    if path is None:
        path = env or os.path.expanduser("~/.cache/kubebatch-tpu/xla")
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path
