"""Trace records, the seeded generator, JSONL I/O, and the replayer.

One record = one gang submission. The generator composes the shape
primitives (workloads/shapes.py) into a deterministic stream — two
generators built from the same (spec, seed, horizon) produce
bit-identical records. A JSONL trace file holds the same schema, one
record per line, so captured or hand-written traces replay through the
identical path.

``TraceReplayer`` is the only driver: it advances a sim clock and
turns due records into pod/podgroup ADDS, due resizes into elastic
grow/shrink events, and due completions into pod/podgroup DELETES —
all through an existing ``sim/source.py`` ``StreamingEventSource``, so
the fold layer, sub-cycles, and the pipelined executor ingest the
firehose exactly the way they ingest everything else.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..objects import (BACKFILL_ANNOTATION, Container,
                       GROUP_NAME_ANNOTATION, Pod, PodGroup, PodPhase,
                       resource_list)
from .elastic import ElasticDriver
from .shapes import (BurstOverlay, DiurnalRate, LognormalSampler,
                     ParetoSampler, poisson_arrivals)

GiB = 1024 ** 3


@dataclass
class TraceRecord:
    """One gang submission. ``tasks`` is the DESIRED member count (the
    gang's pods at arrival); ``min_member`` <= tasks is the quorum — a
    gap makes the gang elastic (AlmostReady-capable). ``resizes`` are
    mid-run desired-size changes, each ``{"dt": seconds-after-arrival,
    "to": new-desired}``. ``duration`` runs from the first moment the
    gang is Running at QUORUM (``min_member`` members) to its
    completion (delete) — elastic extras accelerate a real job but do
    not gate its completion. Gating on full desired size would make
    any gang whose extras starve immortal: it holds its quorum's
    capacity forever, which starves more gangs, and the cluster wedges
    on a feedback loop no admission-calibrated trace intends."""
    t: float
    name: str
    tasks: int
    min_member: int
    duration: float
    cpu_milli: float
    mem_bytes: float
    queue: int = 0
    backfill: bool = False
    resizes: List[Dict[str, float]] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "TraceRecord":
        d = json.loads(line)
        return cls(**d)


@dataclass(frozen=True)
class TraceSpec:
    """A named generator configuration (preset or custom)."""
    name: str
    rate: DiurnalRate
    sizes: ParetoSampler
    durations: LognormalSampler
    burst: BurstOverlay = BurstOverlay()
    cpu_milli: float = 1000.0
    mem_bytes: float = 2 * GiB
    n_queues: int = 2
    #: fraction of gangs with min_member < desired (elastic)
    elastic_fraction: float = 0.0
    #: of the elastic gangs, fraction that fires one mid-run resize
    resize_fraction: float = 0.0
    #: min_member = max(1, ceil(min_frac * desired)) for elastic gangs
    min_frac: float = 0.5
    #: fraction of submissions that are single-pod backfill (lendable)
    backfill_fraction: float = 0.0
    #: analytic approximations for load calibration (Little's law):
    #: steady concurrent tasks ~= rate.base * mean_tasks * mean_duration
    mean_tasks: float = 2.0
    mean_duration: float = 300.0

    def scale_rate(self, factor: float) -> "TraceSpec":
        """Same shapes at ``factor``x the arrival rate — how a caller
        fits a preset to its cluster's headroom."""
        return dataclasses.replace(
            self, rate=dataclasses.replace(self.rate,
                                           base=self.rate.base * factor))


#: the preset catalog (docs/WORKLOADS.md). Rates are in gangs per
#: sim-second and deliberately LOW — callers calibrate with
#: ``scale_rate`` (bench.py sizes offered load to cluster headroom).
PRESETS: Dict[str, TraceSpec] = {
    # Borg-shaped: strong diurnal swing ((1+.6)/(1-.6) = 4x peak/trough
    # over a compressed 6h "day"), heavy-tail gang sizes (alpha 1.8),
    # lognormal durations with a long tail, cron-storm bursts, a lendable
    # best-effort stream, and a modest elastic cohort.
    "borg-diurnal": TraceSpec(
        name="borg-diurnal",
        rate=DiurnalRate(base=0.05, amplitude=0.6, period=21600.0),
        burst=BurstOverlay(every=3600.0, duration=120.0, factor=3.0),
        sizes=ParetoSampler(alpha=1.8, xmin=1.0, lo=1.0, hi=8.0),
        durations=LognormalSampler(mu=5.5, sigma=1.2, lo=60.0,
                                   hi=7200.0),
        elastic_fraction=0.25, resize_fraction=0.6, min_frac=0.5,
        backfill_fraction=0.2,
        mean_tasks=2.4, mean_duration=500.0),
    # ML-training-shaped: larger gangs (alpha 1.5, up to 12), much
    # longer durations, a flatter diurnal (training submits around the
    # clock), a bigger elastic cohort (grow-to-desired is the norm),
    # and a thinner backfill stream.
    "ml-train-heavy": TraceSpec(
        name="ml-train-heavy",
        rate=DiurnalRate(base=0.02, amplitude=0.3, period=21600.0),
        sizes=ParetoSampler(alpha=1.5, xmin=2.0, lo=2.0, hi=12.0),
        durations=LognormalSampler(mu=6.5, sigma=1.0, lo=300.0,
                                   hi=14400.0),
        elastic_fraction=0.4, resize_fraction=0.7, min_frac=0.5,
        backfill_fraction=0.1,
        mean_tasks=3.9, mean_duration=1200.0),
}


def generate_trace(spec: TraceSpec, seed: int, horizon: float,
                   max_jobs: int = 0) -> List[TraceRecord]:
    """The seeded generator: records over [0, horizon) sim-seconds,
    bit-identical per (spec, seed, horizon, max_jobs)."""
    rng = random.Random(seed)
    records: List[TraceRecord] = []
    for t in poisson_arrivals(rng, spec.rate, spec.burst, horizon):
        i = len(records)
        if rng.random() < spec.backfill_fraction:
            rec = TraceRecord(
                t=t, name=f"bf-{i:06d}", tasks=1, min_member=1,
                duration=spec.durations.sample(rng),
                cpu_milli=spec.cpu_milli, mem_bytes=spec.mem_bytes,
                queue=rng.randrange(max(1, spec.n_queues)),
                backfill=True)
        else:
            desired = int(round(spec.sizes.sample(rng)))
            desired = max(1, desired)
            elastic = rng.random() < spec.elastic_fraction and desired > 1
            min_member = (max(1, math.ceil(spec.min_frac * desired))
                          if elastic else desired)
            duration = spec.durations.sample(rng)
            resizes: List[Dict[str, float]] = []
            if elastic and rng.random() < spec.resize_fraction:
                dt = duration * rng.uniform(0.2, 0.6)
                if rng.random() < 0.5:
                    to = desired + max(1, desired // 2)
                else:
                    to = max(min_member, desired - 1)
                if to != desired:
                    resizes.append({"dt": dt, "to": float(to)})
            rec = TraceRecord(
                t=t, name=f"tr-{i:06d}", tasks=desired,
                min_member=min_member, duration=duration,
                cpu_milli=spec.cpu_milli, mem_bytes=spec.mem_bytes,
                queue=rng.randrange(max(1, spec.n_queues)),
                resizes=resizes)
        records.append(rec)
        if max_jobs and len(records) >= max_jobs:
            break
    return records


# ---------------------------------------------------------------------
# JSONL I/O — the same schema on disk
# ---------------------------------------------------------------------

def save_trace(records: List[TraceRecord], path: str) -> None:
    with open(path, "w") as f:
        for rec in records:
            f.write(rec.to_json() + "\n")


def load_trace(path: str) -> List[TraceRecord]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(TraceRecord.from_json(line))
    return records


def resolve_trace(arg: str, seed: int,
                  horizon: float) -> Tuple[str, List[TraceRecord]]:
    """``--trace <preset|path>`` resolution: a preset name generates a
    seeded stream over ``horizon``; anything else must be a JSONL trace
    file. Returns (label, records)."""
    if arg in PRESETS:
        return arg, generate_trace(PRESETS[arg], seed, horizon)
    if os.path.exists(arg):
        return os.path.basename(arg), load_trace(arg)
    raise ValueError(
        f"--trace {arg!r}: not a preset ({sorted(PRESETS)}) and no such "
        f"file")


# ---------------------------------------------------------------------
# the replayer
# ---------------------------------------------------------------------

@dataclass
class _LiveGang:
    record: TraceRecord
    pg: PodGroup
    pods: List[Pod]
    ready_at: Optional[float] = None
    resizes: List[Tuple[float, int]] = field(default_factory=list)
    #: high-water member index — grows name pods from here, NEVER from
    #: len(pods): a reclaimed tenant leaves a hole mid-list, and naming
    #: by length would collide a new pod with a live member's ns/name
    next_idx: int = 0


class TraceReplayer:
    """Drives a record stream into a ``StreamingEventSource``.

    One ``tick()`` advances the sim clock by ``dt`` seconds and emits,
    in order: due arrivals (group add + pod adds), due elastic resizes
    (group update + pod add/delete through ``ElasticDriver``), and due
    completions (pod deletes + group delete). The caller owns the
    scheduler loop and calls ``source.sync()``/``run_cycle`` between
    ticks; ``kubelet(fresh)`` flips this replayer's freshly bound pods
    to Running through the same event stream.
    """

    def __init__(self, records: List[TraceRecord],
                 source, queues: List[str], namespace: str = "sim",
                 dt: float = 1.0, base_timestamp: float = 3e9,
                 on_pod_delete: Optional[Callable[[str], None]] = None):
        self.records = sorted(records, key=lambda r: (r.t, r.name))
        self.source = source
        self.queues = list(queues)
        self.namespace = namespace
        self.dt = dt
        self.base_timestamp = base_timestamp
        self.on_pod_delete = on_pod_delete
        self.clock = 0.0
        self._next = 0
        self.pods_by_uid: Dict[str, Pod] = {}
        self.live: Dict[str, _LiveGang] = {}
        self.elastic = ElasticDriver(source)
        self.stats = {"arrivals": 0, "completions": 0, "grows": 0,
                      "shrinks": 0, "elastic_events": 0,
                      "pods_added": 0, "pods_deleted": 0}

    # -- pod/gang construction ----------------------------------------
    def _make_pod(self, gang: _LiveGang, idx: int) -> Pod:
        rec = gang.record
        annotations = {GROUP_NAME_ANNOTATION: gang.pg.name}
        if rec.backfill:
            annotations[BACKFILL_ANNOTATION] = "true"
        return Pod(
            name=f"{gang.pg.name}-{idx:03d}", namespace=self.namespace,
            annotations=annotations,
            containers=[Container(requests=resource_list(
                cpu=rec.cpu_milli, memory=rec.mem_bytes))],
            creation_timestamp=self.base_timestamp + rec.t + idx / 1e3)

    def _arrive(self, rec: TraceRecord) -> None:
        queue = (self.queues[rec.queue % len(self.queues)]
                 if self.queues else "")
        pg = PodGroup(
            name=rec.name, namespace=self.namespace,
            min_member=rec.min_member, max_member=rec.tasks,
            queue=queue,
            creation_timestamp=self.base_timestamp + rec.t)
        gang = _LiveGang(record=rec, pg=pg, pods=[], next_idx=rec.tasks)
        gang.resizes = [(rec.t + r["dt"], int(r["to"]))
                        for r in rec.resizes]
        self.source.emit_group(pg)
        for i in range(rec.tasks):
            pod = self._make_pod(gang, i)
            self.source.emit_pod(pod)
            gang.pods.append(pod)
            self.pods_by_uid[pod.uid] = pod
        self.live[rec.name] = gang
        self.stats["arrivals"] += 1
        self.stats["pods_added"] += rec.tasks

    def _resize(self, gang: _LiveGang, to: int) -> None:
        have = len(gang.pods)
        if to > have:
            new_pg, added = self.elastic.grow(
                gang.pg, to - have,
                lambda idx: self._make_pod(gang, idx),
                next_index=gang.next_idx)
            gang.next_idx += len(added)
            gang.pods.extend(added)
            for pod in added:
                self.pods_by_uid[pod.uid] = pod
            self.stats["grows"] += 1
            self.stats["pods_added"] += len(added)
            # quorum is unchanged by a grow (min_member stays), so a
            # running gang keeps its completion clock — the new members
            # are extras the allocator binds as capacity allows
        elif to < have:
            new_pg, removed = self.elastic.shrink(gang.pg, gang.pods,
                                                  have - to)
            for pod in removed:
                gang.pods.remove(pod)
                self.pods_by_uid.pop(pod.uid, None)
                if self.on_pod_delete is not None:
                    self.on_pod_delete(pod.uid)
            self.stats["shrinks"] += 1
            self.stats["pods_deleted"] += len(removed)
        else:
            return
        gang.pg = new_pg
        self.stats["elastic_events"] += 1

    def _complete(self, gang: _LiveGang) -> None:
        for pod in gang.pods:
            self.source.emit_pod_delete(pod)
            self.pods_by_uid.pop(pod.uid, None)
            if self.on_pod_delete is not None:
                self.on_pod_delete(pod.uid)
        self.source.emit_group_delete(gang.pg)
        del self.live[gang.record.name]
        self.stats["completions"] += 1
        self.stats["pods_deleted"] += len(gang.pods)

    # -- the clock ------------------------------------------------------
    def tick(self) -> Dict[str, int]:
        """Advance ``dt`` sim-seconds; emit due arrivals, resizes and
        completions. Returns this tick's event counts."""
        before = dict(self.stats)
        self.clock += self.dt
        while (self._next < len(self.records)
               and self.records[self._next].t <= self.clock):
            self._arrive(self.records[self._next])
            self._next += 1
        for gang in list(self.live.values()):
            due = [(t, to) for t, to in gang.resizes if t <= self.clock]
            if due:
                gang.resizes = [(t, to) for t, to in gang.resizes
                                if t > self.clock]
                for _, to in due:
                    self._resize(gang, to)
        for gang in list(self.live.values()):
            if gang.ready_at is None:
                # the completion clock starts at QUORUM, not at full
                # desired size (see TraceRecord.duration)
                running = sum(1 for p in gang.pods
                              if p.phase == PodPhase.RUNNING)
                if gang.pods and running >= max(1, gang.pg.min_member):
                    gang.ready_at = self.clock
            elif self.clock >= gang.ready_at + gang.record.duration:
                self._complete(gang)
        return {k: self.stats[k] - before[k] for k in self.stats}

    def inject_elastic(self) -> bool:
        """The ``workload.elastic`` fault seam's hook: when an armed
        plan fires it, grow ONE fully-running live gang by a pod —
        desired rises mid-run via a group update, exactly the
        chaos-soak discipline (the caller's quiesce gate requires the
        grown pod to bind). Call once per cycle; a no-fire is free."""
        for name in sorted(self.live):
            gang = self.live[name]
            if gang.record.backfill or gang.ready_at is None:
                continue
            grown = self.elastic.maybe_inject(
                gang.pg, gang.pods,
                lambda idx: self._make_pod(gang, idx),
                next_index=gang.next_idx)
            if grown is None:
                return False       # one candidate per tick; seam decides
            gang.pg, added = grown
            gang.next_idx += len(added)
            gang.pods.extend(added)
            for pod in added:
                self.pods_by_uid[pod.uid] = pod
            self.stats["grows"] += 1
            self.stats["elastic_events"] += 1
            self.stats["pods_added"] += len(added)
            return True
        return False

    def kill_pod(self, uid: str) -> None:
        """The evictor seam's hook: the cluster deletes an evicted pod
        (a reclaimed backfill tenant). The pod leaves its gang through
        the event stream; a gang emptied by the kill is completed
        early (its group deleted) rather than left a zombie."""
        pod = self.pods_by_uid.pop(uid, None)
        if pod is None:
            return
        self.source.emit_pod_delete(pod)
        if self.on_pod_delete is not None:
            self.on_pod_delete(uid)
        self.stats["pods_deleted"] += 1
        gname = pod.annotations.get(GROUP_NAME_ANNOTATION, "")
        gang = self.live.get(gname)
        if gang is None:
            return
        gang.pods = [p for p in gang.pods if p.uid != uid]
        if not gang.pods:
            self.source.emit_group_delete(gang.pg)
            del self.live[gname]
            self.stats["completions"] += 1

    def kubelet(self, fresh_pods: List[Pod]) -> None:
        """Flip THIS replayer's freshly bound pods to Running via the
        event stream (pods it does not own are left to the caller)."""
        for pod in fresh_pods:
            if (pod.uid in self.pods_by_uid
                    and pod.phase == PodPhase.PENDING
                    and pod.node_name):
                pod.phase = PodPhase.RUNNING
                self.source.emit_pod_update(pod, pod)

    @property
    def exhausted(self) -> bool:
        """All records delivered and every delivered gang completed."""
        return self._next >= len(self.records) and not self.live
