"""workloads — the trace-replay workload plane (ROADMAP item 3).

Three pillars, layered on the EXISTING ingestion path (the
``sim/source.py`` streaming pump — no new way into the cache):

- ``shapes``: composable seeded distributions — diurnal sinusoid
  arrival rates, heavy-tail Pareto/lognormal sizes and durations,
  burst episodes.
- ``trace``: the trace record schema, the seeded generator with its
  named presets (``borg-diurnal``, ``ml-train-heavy``), the JSONL
  loader/dumper, and ``TraceReplayer`` — the driver that turns a
  record stream into pod/podgroup adds, delayed completions, and
  elastic resize events through a ``StreamingEventSource``.
- ``elastic``: grow/shrink mechanics for gangs with
  ``min_member != max_member`` (the ``workload.elastic`` fault seam's
  host), riding ``emit_group_update`` + pod add/delete.

See docs/WORKLOADS.md for the schema, the preset catalog, and the
backfill-over-reserved state machine the replayed gangs exercise.
"""
from .elastic import ElasticDriver
from .shapes import (BurstOverlay, DiurnalRate, LognormalSampler,
                     ParetoSampler)
from .trace import (PRESETS, TraceRecord, TraceReplayer, TraceSpec,
                    generate_trace, load_trace, resolve_trace, save_trace)

__all__ = [
    "BurstOverlay", "DiurnalRate", "ElasticDriver", "LognormalSampler",
    "PRESETS", "ParetoSampler", "TraceRecord", "TraceReplayer",
    "TraceSpec", "generate_trace", "load_trace", "resolve_trace",
    "save_trace",
]
