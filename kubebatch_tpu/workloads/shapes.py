"""Composable workload shape primitives.

Everything here is a pure function of a caller-owned
``random.Random`` and the trace clock, so two generators built from
the same seed produce bit-identical streams (pinned in
tests/test_workloads.py). The shapes follow the Borg workload-trace
characterizations the lineage papers lean on: arrival rates swing
diurnally (a sinusoid over a day-shaped period), job sizes and
durations are heavy-tailed (Pareto / lognormal — a few giants dominate
the mass), and submission is bursty (short episodes of multiplied
rate, e.g. cron storms and retry stampedes).
"""
from __future__ import annotations

import math
import random
from dataclasses import dataclass


@dataclass(frozen=True)
class DiurnalRate:
    """Arrival rate lambda(t) = base * (1 + amplitude*sin(2*pi*t/period)).

    ``amplitude`` in [0, 1): the peak/trough ratio is
    (1+a)/(1-a) — 0.6 gives the ~4x day/night swing of the Borg traces.
    ``phase`` shifts the peak (fraction of a period).
    """
    base: float
    amplitude: float = 0.0
    period: float = 86400.0
    phase: float = 0.0

    def rate(self, t: float) -> float:
        if self.amplitude <= 0.0:
            return self.base
        x = 2.0 * math.pi * (t / self.period + self.phase)
        return self.base * (1.0 + self.amplitude * math.sin(x))

    @property
    def max_rate(self) -> float:
        return self.base * (1.0 + max(0.0, self.amplitude))


@dataclass(frozen=True)
class BurstOverlay:
    """Burst episodes over a base rate: while inside an episode the
    rate is multiplied by ``factor``. Episodes recur every ``every``
    seconds (from the episode-grid origin) and last ``duration``
    seconds — deterministic placement, so the same seed replays the
    same storms."""
    every: float = 0.0
    duration: float = 0.0
    factor: float = 1.0

    def multiplier(self, t: float) -> float:
        if self.every <= 0.0 or self.duration <= 0.0 or self.factor == 1.0:
            return 1.0
        return self.factor if (t % self.every) < self.duration else 1.0

    @property
    def max_multiplier(self) -> float:
        if self.every <= 0.0 or self.duration <= 0.0:
            return 1.0
        return max(1.0, self.factor)


@dataclass(frozen=True)
class ParetoSampler:
    """Heavy-tail sampler: P(X > x) = (xmin/x)^alpha for x >= xmin.

    ``alpha`` is the tail index (smaller = heavier; Borg task-count
    tails sit around 1.5-2.5). Samples clamp to [lo, hi] when bounds
    are given — gang sizes must stay schedulable on the sim cluster.
    """
    alpha: float
    xmin: float = 1.0
    lo: float = 0.0
    hi: float = 0.0

    def sample(self, rng: random.Random) -> float:
        u = 1.0 - rng.random()        # (0, 1]
        x = self.xmin / (u ** (1.0 / self.alpha))
        if self.lo:
            x = max(self.lo, x)
        if self.hi:
            x = min(self.hi, x)
        return x


@dataclass(frozen=True)
class LognormalSampler:
    """Lognormal sampler (mu/sigma in log space), clamped like
    ParetoSampler. The duration workhorse: most jobs are short, the
    tail runs for hours."""
    mu: float
    sigma: float
    lo: float = 0.0
    hi: float = 0.0

    def sample(self, rng: random.Random) -> float:
        x = rng.lognormvariate(self.mu, self.sigma)
        if self.lo:
            x = max(self.lo, x)
        if self.hi:
            x = min(self.hi, x)
        return x


def poisson_arrivals(rng: random.Random, rate: DiurnalRate,
                     burst: BurstOverlay, horizon: float):
    """Arrival times of a non-homogeneous Poisson process over
    [0, horizon) via thinning: candidate points at the envelope rate,
    accepted with probability lambda(t)/envelope. One rng, consumed in
    a fixed order — bit-identical per seed."""
    envelope = rate.max_rate * burst.max_multiplier
    if envelope <= 0.0:
        return
    t = 0.0
    while True:
        t += rng.expovariate(envelope)
        if t >= horizon:
            return
        lam = rate.rate(t) * burst.multiplier(t)
        if rng.random() * envelope < lam:
            yield t
