"""Elastic gang mechanics: grow/shrink a live PodGroup mid-run.

A gang is elastic when ``min_member < max_member``: it schedules at its
quorum (AlmostReady) and backfills toward the desired size. This module
owns the event-side mechanics — a resize is an ordinary
``emit_group_update`` (min/max change) plus pod adds or deletes through
the SAME streaming source everything else rides, so the fold layer and
the pipelined executor's flight-window fingerprint see it like any
other churn.

``ElasticDriver.maybe_inject`` is the ``workload.elastic`` fault seam's
host: the chaos soak (sim/chaos.py) crosses it every cycle, and a fired
seam forces a grow onto a live gang at adversarial timing — between
solve launch and consume under the pipelined executor, where a stale
in-flight result against the resized gang would double-bind unless the
fingerprint invalidates it.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

from .. import faults
from ..objects import Pod, PodGroup, PodPhase


class ElasticDriver:
    """Applies grow/shrink events to live gangs through a
    ``StreamingEventSource`` (sim/source.py)."""

    def __init__(self, source):
        self.source = source
        #: counters for evidence lines (bench soak / chaos report)
        self.grows = 0
        self.shrinks = 0
        self.injected = 0

    def grow(self, pg: PodGroup, n: int,
             make_pod: Callable[[int], Pod],
             next_index: int) -> Tuple[PodGroup, List[Pod]]:
        """Raise the gang's desired size by ``n``: group update first
        (the membership contract changes before the pods exist, exactly
        like a real controller scaling up), then ``n`` new member pods
        built by ``make_pod(index)`` starting at ``next_index``.

        ``next_index`` MUST be monotonic over the gang's lifetime (a
        high-water member counter), never ``len(pods)``: after a mid-
        list eviction (a reclaimed backfill tenant), the list length
        equals a LIVE member's index, and reusing it would collide two
        pods on one ns/name key in the scheduler cache."""
        new_desired = next_index + n
        new_pg = dataclasses.replace(
            pg, max_member=max(new_desired, pg.min_member))
        self.source.emit_group_update(pg, new_pg)
        added = []
        for i in range(n):
            pod = make_pod(next_index + i)
            self.source.emit_pod(pod)
            added.append(pod)
        self.grows += 1
        return new_pg, added

    def shrink(self, pg: PodGroup, pods: List[Pod],
               n: int) -> Tuple[PodGroup, List[Pod]]:
        """Lower the gang's desired size by ``n``: delete the ``n``
        least-committed members (pending before running, newest first),
        then shrink the membership contract — never below one member.
        ``min_member`` follows the new desired size down when it would
        otherwise exceed it."""
        n = min(n, max(0, len(pods) - 1))
        if n <= 0:
            return pg, []
        pending = [p for p in reversed(pods) if p.phase == PodPhase.PENDING]
        running = [p for p in reversed(pods) if p.phase != PodPhase.PENDING]
        victims = (pending + running)[:n]
        for pod in victims:
            self.source.emit_pod_delete(pod)
        new_desired = len(pods) - n
        new_pg = dataclasses.replace(
            pg, max_member=new_desired,
            min_member=min(pg.min_member, new_desired))
        self.source.emit_group_update(pg, new_pg)
        self.shrinks += 1
        return new_pg, victims

    def maybe_inject(self, pg: PodGroup, pods: List[Pod],
                     make_pod: Callable[[int], Pod],
                     next_index: Optional[int] = None
                     ) -> Optional[Tuple[PodGroup, List[Pod]]]:
        """The ``workload.elastic`` seam crossing: when the armed fault
        plan fires, force a one-member grow onto the live gang ``pg``
        RIGHT NOW — the caller sits between solve launch and consume,
        so the resize lands mid-flight. Returns (new_pg, added) when
        the seam fired, None otherwise. ``next_index`` defaults to
        ``len(pods)`` — callers whose gangs can lose mid-list members
        (evicted tenants) must pass their monotonic counter (see grow)."""
        if not faults.should_fail("workload.elastic"):
            return None
        if next_index is None:
            next_index = len(pods)
        new_pg, added = self.grow(pg, 1, make_pod, next_index=next_index)
        self.injected += 1
        return new_pg, added
