"""The span tracer — one structured timing system for the whole cycle.

Every legacy ``time.perf_counter()`` pair in the scheduler loop, the
actions, the kernels and the rpc layer routes through here: a span is a
named, categorized interval in a per-cycle tree (cycle -> action -> host
phase -> kernel dispatch -> blocking readback), and the OLD accounting —
``metrics.update_host_phase``, ``update_solver_kernel_duration``,
``update_action_duration``, ``update_plugin_duration``,
``update_tensorize_duration``, the jax-profiler ``solver_trace``
annotation — is a DERIVED VIEW fired at span exit. Callers that pinned
those counters (bench.py ``host_phase_ms``, the readback budget tests)
keep working unchanged; the span tree is strictly additive evidence.

Overhead discipline (the ISSUE 7 budget: tracing-on steady cycles within
2% of tracing-off, enforced by tests/test_obs.py):

- a span enter/exit costs two ``perf_counter`` calls, one small object,
  and one list append — no locks, no dict lookups on the hot path;
- tree RETENTION only happens inside an open cycle root. A span closed
  with no root still fires its derived metric views (bench drives
  sessions without the scheduler loop) and is then dropped, so ad-hoc
  calls can never accumulate memory;
- ``set_enabled(False)`` disables tree construction entirely (no stack
  push, no child lists) but NEVER the derived views — the tracing-off
  half of an A/B still accounts identically while paying only the Span
  object and its two timestamps.

Thread model: one tree per thread (``threading.local``). The scheduler
loop owns its cycle root; rpc server handler threads open their own
per-request root (``server_root``) and serialize it back to the client,
which grafts it under its rpc span — that is how server-side solve spans
stitch into the client's cycle tree without touching the wire schema.
"""
from __future__ import annotations

import itertools as _it
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import metrics

__all__ = ["Span", "span", "begin_cycle", "end_cycle", "current_cycle",
           "current_epoch", "last_cycle", "set_enabled", "enabled",
           "cycle", "begin_server_root", "end_server_root", "graft",
           "add_event", "arm_profile", "span_overhead_estimate",
           "CYCLE_HOOKS", "SPAN_HOOKS", "tracer_stats", "spans_total"]

_perf = time.perf_counter


class Span:
    """One timed interval. ``t0``/``dur`` are perf_counter seconds;
    ``cat`` drives the derived metric view at exit (see _DERIVED)."""

    __slots__ = ("name", "cat", "t0", "dur", "args", "children")

    def __init__(self, name: str, cat: str, args: Optional[dict] = None):
        self.name = name
        self.cat = cat
        self.t0 = 0.0
        self.dur = 0.0
        self.args = args
        self.children: List["Span"] = []

    # -- serialization (flight recorder + rpc stitching) ----------------
    def to_dict(self) -> dict:
        d: Dict = {"name": self.name, "cat": self.cat,
                   "t0": self.t0, "dur": self.dur}
        if self.args:
            d["args"] = dict(self.args)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        sp = cls(d.get("name", "?"), d.get("cat", "host"),
                 dict(d["args"]) if d.get("args") else None)
        sp.t0 = float(d.get("t0", 0.0))
        sp.dur = float(d.get("dur", 0.0))
        sp.children = [cls.from_dict(c) for c in d.get("children", ())]
        return sp

    def count(self) -> int:
        """Number of spans in this subtree (spans_per_cycle evidence)."""
        return 1 + sum(c.count() for c in self.children)

    def shift(self, delta: float) -> None:
        """Rebase the subtree's timestamps by ``delta`` seconds (used when
        grafting a remote tree whose perf_counter base is another
        process's)."""
        self.t0 += delta
        for c in self.children:
            c.shift(delta)

    def find(self, name: str) -> Optional["Span"]:
        """Depth-first lookup by span name (tests / diagnostics)."""
        if self.name == name:
            return self
        for c in self.children:
            hit = c.find(name)
            if hit is not None:
                return hit
        return None


# ---------------------------------------------------------------------
# per-thread tree state
# ---------------------------------------------------------------------

_TLS = threading.local()

#: retention switch — derived metric views fire regardless (see module
#: docstring); guarded by nothing, it is a read-mostly bool
_ENABLED = True

#: hooks called with the finished root span at every cycle end (flight
#: recorder + trace exporter register here; hooks must never raise)
CYCLE_HOOKS: List[Callable[[Span], None]] = []

#: hooks called with EVERY finished span on a clean exit (the decision
#: ledger stamps stage transitions here — obs/ledger.py registers at
#: import). The empty-list check is the only hot-path cost; a
#: registered hook shares the per-span overhead budget test_obs pins,
#: so hooks must be a few dict ops at most and must never raise.
SPAN_HOOKS: List[Callable[[Span], None]] = []

#: the most recent finished cycle root on ANY thread (diagnostics; the
#: scheduler is single-threaded so last-writer-wins is exact there)
_last_cycle: Optional[Span] = None

#: process-lifetime span count (consumers diff across a window, like
#: every other counter in metrics.py)
_spans_total = 0

#: monotonically increasing cycle-epoch sequence, stamped on every cycle
#: root's args (ISSUE 16): with the pipelined executor a span can close
#: inside a DIFFERENT cycle's root than the one that launched its work
#: (the consume of cycle N's solve runs under cycle N+1), so the epoch
#: tag — not tree position — is what attributes overlapped work to its
#: launching cycle. Never reset; GIL-atomic via itertools.count.
_epoch_seq = _it.count(1)


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def now() -> float:
    """The tracer's clock (perf_counter seconds) — for milestone probes
    that want timestamps comparable with span t0/dur without importing
    their own timing source."""
    return _perf()


def set_enabled(on: bool) -> None:
    """Toggle tree retention (the A/B switch for the overhead budget
    test). Derived metric views are unaffected."""
    global _ENABLED
    _ENABLED = bool(on)


def enabled() -> bool:
    return _ENABLED


def spans_total() -> int:
    """Process-lifetime completed-span count; consumers diff a window."""
    return _spans_total


# ---------------------------------------------------------------------
# derived metric views (the old accounting, fired at span exit)
# ---------------------------------------------------------------------

def _exit_phase(sp: Span) -> None:
    metrics.update_host_phase(sp.name, sp.dur)


def _exit_kernel(sp: Span) -> None:
    metrics.update_solver_kernel_duration(sp.name, sp.dur)


def _exit_action(sp: Span) -> None:
    metrics.update_action_duration(sp.name, sp.dur)


def _exit_plugin(sp: Span) -> None:
    metrics.update_plugin_duration(sp.name,
                                   (sp.args or {}).get("phase", ""), sp.dur)


def _exit_tensorize(sp: Span) -> None:
    metrics.update_tensorize_duration(sp.dur)


def _exit_e2e(sp: Span) -> None:
    metrics.update_e2e_duration(sp.dur)


_DERIVED = {
    "phase": _exit_phase,
    "kernel": _exit_kernel,
    "action": _exit_action,
    "plugin": _exit_plugin,
    "tensorize": _exit_tensorize,
    "e2e": _exit_e2e,
}


#: categories whose derived view also fires on an EXCEPTION exit —
#: matching the pre-migration accounting exactly where it matters: the
#: old tensorize/replay/e2e sites updated from try/finally (partial wall
#: counted), while the old kernel/action/plugin sites updated after the
#: work and skipped on a raise (an aborted dispatch must not inflate
#: solver_kernel_seconds across a fault window).
_VIEW_ON_ERROR = frozenset({"phase", "e2e"})


class _SpanCtx:
    """The context manager ``span()`` returns. Kernel-cat spans also
    enter the jax-profiler annotation (metrics.solver_trace), so a
    surrounding profiler session — including the gated --profile-cycles
    capture — sees the same names the span tree carries.

    When retention is disabled (set_enabled(False), the A/B off arm)
    the span never touches the thread stack or any parent's child list —
    the off cost is the Span object, two perf_counter calls, and the
    derived view; tree construction is genuinely off, so the overhead
    budget test compares something real."""

    __slots__ = ("sp", "_trace", "_pushed")

    def __init__(self, sp: Span):
        self.sp = sp
        self._trace = None
        self._pushed = False

    def __enter__(self) -> Span:
        sp = self.sp
        if _ENABLED:
            st = _stack()
            if st:
                st[-1].children.append(sp)
            st.append(sp)
            self._pushed = True
        if sp.cat == "kernel":
            self._trace = metrics.solver_trace(sp.name)
            self._trace.__enter__()
        sp.t0 = _perf()
        return sp

    def __exit__(self, exc_type, exc, tb) -> None:
        sp = self.sp
        sp.dur = _perf() - sp.t0
        if self._trace is not None:
            self._trace.__exit__(exc_type, exc, tb)
        if self._pushed:
            st = _stack()
            # pop THIS span; a hook that raised mid-tree must not desync
            # the stack, so tolerate (and repair) a non-top position
            if st and st[-1] is sp:
                st.pop()
            elif sp in st:                 # pragma: no cover — repair path
                while st and st[-1] is not sp:
                    st.pop()
                if st:
                    st.pop()
        global _spans_total
        _spans_total += 1
        if exc_type is None or sp.cat in _VIEW_ON_ERROR:
            view = _DERIVED.get(sp.cat)
            if view is not None:
                view(sp)
            if SPAN_HOOKS and exc_type is None:
                # clean exits only: an aborted dispatch must not stamp a
                # ledger stage it never completed
                try:
                    for hook in SPAN_HOOKS:
                        hook(sp)
                except Exception:          # pragma: no cover — hook bug
                    pass
        if not _ENABLED or (self._pushed and not _stack()):
            sp.children = []               # retention off / rootless: drop


def span(name: str, cat: str = "host", **args) -> _SpanCtx:
    """Open a child span under the current thread's tree.

    ``cat`` picks the derived metric view fired at exit:
    "phase" -> update_host_phase, "kernel" -> update_solver_kernel_duration
    (+ jax TraceAnnotation), "action"/"plugin"/"tensorize"/"e2e" -> their
    histogram updaters, anything else -> span-tree only ("host", "rpc",
    "readback", "compile", "probe").
    """
    return _SpanCtx(Span(name, cat, args or None))


# ---------------------------------------------------------------------
# cycle roots
# ---------------------------------------------------------------------

def begin_cycle(cycle_id: Optional[int] = None, name: str = "cycle",
                **args) -> Span:
    """Open a cycle root span on this thread. Pair with end_cycle in a
    try/finally — the scheduler needs the measured duration after exit
    (deadline budget), which a plain with-statement can't give it.
    ``name`` labels the root ("cycle" for the period loop; the
    schedule-on-arrival path opens "subcycle" roots, which therefore
    appear as their own span roots in Chrome traces and the flight
    ring — same tree machinery, no second tracer). Every root carries a
    process-unique ``epoch`` arg: spans that outlive their cycle (the
    pipelined consume closes inside the NEXT cycle's root) are tagged
    with the launching root's epoch, so trace consumers attribute them
    by epoch rather than by tree position."""
    if cycle_id is not None:
        args["cycle"] = cycle_id
    args["epoch"] = next(_epoch_seq)
    root = Span(name, "cycle", args)
    if _ENABLED:
        st = _stack()
        if st:                             # nested cycle: plain child span
            st[-1].children.append(root)
        st.append(root)
    _profile_cycle_begin()
    root.t0 = _perf()
    return root


def end_cycle(root: Span, **args) -> Span:
    """Close a cycle root: stamps dur, fires the cycle hooks (flight
    recorder ring + trace exporter), clears this root's stack frame.

    Overlapping roots (ISSUE 16): a cycle root that is still OPEN when
    an earlier root ends is not a straggler — it is detached from the
    ending root's tree and kept live on the stack, so it finishes as an
    independent root with its own hook firing and a complete tree of
    its own (two overlapping roots export as two valid Chrome-trace
    trees). Only non-cycle spans left open above the ending root (a
    raising action) are swept."""
    root.dur = _perf() - root.t0
    if args:
        root.args = dict(root.args or {}, **args)
    st = _stack()
    nested = False
    if root in st:             # not pushed at all when retention was off
        i = st.index(root)
        # an older cycle root still open BELOW this one means this root
        # is properly nested (subcycle style): its parent fires the hooks
        nested = any(s.cat == "cycle" for s in st[:i])
        above = st[i:]
        del st[i:]
        for j in range(1, len(above)):
            if above[j].cat == "cycle":
                # a younger overlapping root (and everything it opened):
                # detach it from the ending root's tree and re-push
                parent = above[j - 1]
                if above[j] in parent.children:
                    parent.children.remove(above[j])
                st.extend(above[j:])
                break
    else:
        nested = any(s.cat == "cycle" for s in st)
    global _spans_total, _last_cycle
    _spans_total += 1          # descendants already counted at their exit
    _profile_cycle_end()
    # outermost CYCLE root (plain host spans around it — the loop
    # tick — don't make it "nested"): fire the cycle hooks
    if not nested:
        _last_cycle = root
        if _ENABLED:
            for hook in CYCLE_HOOKS:
                try:
                    hook(root)
                except Exception:          # a hook must never fail a cycle
                    import logging
                    logging.getLogger("kubebatch.obs").exception(
                        "cycle hook failed")
    return root


class _CycleCtx:
    __slots__ = ("root",)

    def __init__(self, root: Span):
        self.root = root

    def __enter__(self) -> Span:
        return self.root

    def __exit__(self, exc_type, exc, tb) -> None:
        end_cycle(self.root,
                  **({"error": exc_type.__name__} if exc_type else {}))


def cycle(cycle_id: Optional[int] = None, **args) -> _CycleCtx:
    """``with obs.cycle(i) as root:`` — the with-statement form of
    begin_cycle/end_cycle for callers that don't need the duration after
    exit (bench, tests)."""
    return _CycleCtx(begin_cycle(cycle_id, **args))


def current_cycle() -> Optional[Span]:
    """This thread's innermost open CYCLE span, or None — with
    overlapping roots the innermost is the cycle currently being BUILT
    (the older one is only waiting for its in-flight work)."""
    st = getattr(_TLS, "stack", None)
    if not st:
        return None
    for s in reversed(st):
        if s.cat == "cycle":
            return s
    return None


def current_epoch() -> Optional[int]:
    """The ``epoch`` tag of this thread's current cycle root, or None.
    Work launched now and consumed inside a LATER cycle stamps this on
    its consume span, attributing it to the launching cycle."""
    sp = current_cycle()
    return (sp.args or {}).get("epoch") if sp is not None else None


def last_cycle() -> Optional[Span]:
    """The most recently finished cycle root (any thread)."""
    return _last_cycle


def add_event(name: str, dur: float, cat: str = "compile", **args) -> None:
    """Attach an already-finished interval (ending NOW) to the current
    open span — how compilesvc's jax.monitoring listener lands XLA
    compile events inside the cycle tree without wrapping the compiler."""
    st = getattr(_TLS, "stack", None)
    if not st:
        return
    sp = Span(name, cat, args or None)
    sp.dur = dur
    sp.t0 = _perf() - dur
    st[-1].children.append(sp)


# ---------------------------------------------------------------------
# rpc stitching
# ---------------------------------------------------------------------

def begin_server_root(name: str = "sidecar", **args) -> Span:
    """Per-request root for an rpc handler thread. Same mechanics as a
    cycle root but marked remote: the exporter gives it its own pid lane
    and end-of-request serialization ships it back to the client."""
    root = Span(name, "remote", dict(args, remote=True))
    _stack().append(root)
    root.t0 = _perf()
    return root


def end_server_root(root: Span) -> Span:
    root.dur = _perf() - root.t0
    st = _stack()
    while st and st[-1] is not root:
        st.pop()
    if st:
        st.pop()
    global _spans_total
    _spans_total += 1          # descendants already counted at their exit
    return root


def graft(parent: Span, remote: Span) -> None:
    """Attach a deserialized remote tree under ``parent`` (the client's
    rpc span), rebasing its timestamps: the remote perf_counter base is
    another process's, so the remote root is centered inside the parent
    span (the unsynchronized-clock convention for one-shot RPCs — the
    DURATIONS are measured, only the offset is aligned)."""
    delta = (parent.t0 + max(0.0, (parent.dur - remote.dur) / 2.0)
             - remote.t0)
    remote.shift(delta)
    parent.children.append(remote)


# ---------------------------------------------------------------------
# gated jax.profiler programmatic capture (--profile-cycles N)
# ---------------------------------------------------------------------

_profile_state = {"remaining": 0, "dir": "", "active": False}


def arm_profile(cycles: int, directory: str) -> None:
    """Capture a jax.profiler trace covering the next ``cycles`` cycle
    roots into ``directory`` (the same trace dir the Chrome export uses,
    so host spans and device timelines land side by side)."""
    _profile_state["remaining"] = int(cycles)
    _profile_state["dir"] = directory


def _profile_cycle_begin() -> None:
    ps = _profile_state
    if ps["remaining"] > 0 and not ps["active"]:
        try:
            import jax.profiler as _prof
            _prof.start_trace(ps["dir"])
            ps["active"] = True
        except Exception:                  # never fail a cycle for a trace
            ps["remaining"] = 0


def _profile_cycle_end() -> None:
    ps = _profile_state
    if not ps["active"]:
        return
    ps["remaining"] -= 1
    if ps["remaining"] <= 0:
        try:
            import jax.profiler as _prof
            _prof.stop_trace()
        except Exception:                  # pragma: no cover
            pass
        ps["active"] = False


# ---------------------------------------------------------------------
# overhead evidence (bench.py trace_overhead_ms)
# ---------------------------------------------------------------------

_overhead_estimate: Optional[float] = None


def span_overhead_estimate(samples: int = 2000) -> float:
    """Measured per-span cost in SECONDS on this box (enter+exit of a
    retention-on span), calibrated once per process. bench multiplies by
    spans_per_cycle to report trace_overhead_ms — a calibrated estimate,
    labeled as such, instead of doubling every hot-path timestamp to
    self-measure."""
    global _overhead_estimate
    if _overhead_estimate is None:
        with cycle(None):                  # retention on, realistic path
            t0 = _perf()
            for _ in range(samples):
                with span("calib", cat="host"):
                    pass
            _overhead_estimate = (_perf() - t0) / samples
    return _overhead_estimate


def tracer_stats() -> dict:
    """Snapshot for /debug/vars and bench lines."""
    lc = _last_cycle
    return {
        "enabled": _ENABLED,
        "spans_total": _spans_total,
        "last_cycle_spans": lc.count() if lc is not None else 0,
        "span_overhead_us": (round(_overhead_estimate * 1e6, 3)
                             if _overhead_estimate is not None else None),
    }
