"""The per-pod decision-latency ledger (ISSUE 17).

Every pod that the scheduler binds gets ONE closed ledger record telling
the full latency story of its decision:

    arrival -> fold -> pack -> solve -> apply -> bind

- **arrival** is stamped by the cache the moment a PENDING pod enters
  (``cache/cache.py _fire_arrival_hooks`` — the same funnel the
  schedule-on-arrival sub-cycle rides, so the stamp exists whether or
  not any hook is registered);
- **fold / pack / solve** are stamped from span exits (``SPAN_HOOKS`` in
  obs/spans.py): the "fold" phase span, the "tensorize" phase span and
  any ``cat="kernel"`` dispatch span mark their cycle epoch's stage
  completion times — keyed by EPOCH, not wall order, because with the
  pipelined executor cycle k's solve can be consumed inside cycle k+1;
- **apply** is stamped directly by ``cache.bind``/``bind_many`` at
  entry (the decision-apply funnel all three bind paths share);
- **bind** closes the record: the cache calls :func:`close` per pod at
  the state flip, the moment the decision is durably applied.

The pipelined executor's deferred-consume path closes records under an
:func:`attribute` context carrying the LAUNCHING cycle's epoch and
``deferred=True`` — fold/pack/solve stamps come from epoch k, apply from
the consuming epoch k+1, and the record says so, attributing the overlap
window honestly. An invalidated in-flight cycle closes nothing (its
decisions are discarded); the same cycle's sequential re-solve closes
the records through the ordinary bind funnel.

Closed records land in lock-free-read, log-bucketed **streaming
histograms** keyed ``(lane, tenant, engine)`` — 8 buckets per octave
(~9% relative resolution), sparse dict storage, O(1) memory per key —
plus per-(lane, stage) stage-duration histograms, the sub-cycle
arrival histogram that ``metrics.arrival_latency_percentiles`` now
reads (the old raw-list reservoir is deprecated), and per-(tenant,
lane) admission-wait histograms fed by tenantsvc admission.

Consumers read percentiles over a WINDOW (:func:`window` captures a
snapshot; the window object diffs live state against it) — this is what
replaced bench.py's hand-rolled arrival/sustained percentile math.

Bounded by construction: the open-record map evicts its oldest entry
past ``MAX_OPEN`` (counted, never silent), per-epoch stage maps keep the
last ``EPOCH_KEEP`` epochs, histogram key cardinality caps at
``MAX_KEYS`` (overflow keys aggregate into ``("other","other","other")``),
and closed-record retention (chaos/test audit mode) is OFF by default.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["LANE_ANNOTATION", "LATENCY_LANE", "DEFAULT_LANE", "STAGES",
           "StreamHist", "LedgerWindow", "set_enabled", "enabled",
           "reset", "stamp_arrival", "discard", "stage_mark", "close",
           "attribute", "on_span_exit", "observe_subcycle_arrival",
           "subcycle_percentiles", "observe_admission", "window",
           "percentile", "stats", "retain", "stop_retention", "retained",
           "MAX_OPEN", "MAX_KEYS"]

#: the lane annotation vocabulary (single source of truth — runtime/
#: subcycle.py and tenantsvc re-export these; the tenantsvc lane names
#: ride the same key)
LANE_ANNOTATION = "scheduling.k8s.io/kube-batch/lane"
LATENCY_LANE = "latency"
DEFAULT_LANE = "normal"

#: stage order between arrival and bind; close() drops stamps that
#: precede the pod's arrival (a pod that arrived mid-cycle honestly has
#: no fold/pack story for that cycle) and clamps the rest monotone
STAGES = ("fold", "pack", "solve", "apply")

#: open-arrival map bound: past this the OLDEST open record is evicted
#: (counted in stats()["evicted_total"]) so deleted-while-pending pods
#: can never leak the map unbounded even if discard() is missed
MAX_OPEN = 65536

#: histogram key-cardinality bound; excess (lane, tenant, engine) keys
#: aggregate into the overflow key instead of growing without bound
MAX_KEYS = 256
_OVERFLOW_KEY = ("other", "other", "other")

#: per-epoch stage maps kept (the pipelined executor defers by exactly
#: one cycle; 64 epochs is deep slack for nested subcycle roots)
EPOCH_KEEP = 64

_perf_now = None  # bound lazily to spans.now so both share one clock


def _now() -> float:
    global _perf_now
    if _perf_now is None:
        from . import spans as _spans
        _perf_now = _spans.now
    return _perf_now()


# ---------------------------------------------------------------------
# log-bucketed streaming histogram
# ---------------------------------------------------------------------

#: sub-buckets per octave: bucket index = floor(log2(v) * FINE); the
#: relative bucket width is 2**(1/8)-1 ~ 9%, so a bucket-midpoint
#: percentile answer is within ~4.5% of the true order statistic
FINE = 8
_MIN_V = 1e-7                      # 0.1us floor; <=0 clamps here
_LOG2 = math.log(2.0)


def _bucket_idx(v: float) -> int:
    if v < _MIN_V:
        v = _MIN_V
    return int(math.floor(math.log(v) / _LOG2 * FINE))


def _bucket_mid(idx: int) -> float:
    return 2.0 ** ((idx + 0.5) / FINE)


def _bucket_upper(idx: int) -> float:
    return 2.0 ** ((idx + 1.0) / FINE)


class StreamHist:
    """A sparse log-bucketed streaming histogram of SECONDS.

    Single-writer increments are GIL-atomic per bucket; the ledger
    serializes writers under its module lock anyway. ``snapshot()``
    copies are what window consumers diff — reads never block writes.
    """

    __slots__ = ("count", "sum", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.buckets: Dict[int, int] = {}

    def observe(self, seconds: float) -> None:
        idx = _bucket_idx(seconds)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        self.count += 1
        self.sum += seconds

    def snapshot(self) -> Tuple[int, float, Dict[int, int]]:
        return self.count, self.sum, dict(self.buckets)


def _pct_from_counts(counts: Dict[int, int], p: float) -> Optional[float]:
    """Percentile (seconds) from merged bucket counts: the geometric
    midpoint of the bucket holding the ceil-rank order statistic."""
    total = sum(counts.values())
    if total <= 0:
        return None
    rank = max(1, int(math.ceil(p / 100.0 * total)))
    acc = 0
    for idx in sorted(counts):
        acc += counts[idx]
        if acc >= rank:
            return _bucket_mid(idx)
    return _bucket_mid(max(counts))    # pragma: no cover — rank <= total


def _max_from_counts(counts: Dict[int, int]) -> Optional[float]:
    live = [i for i, n in counts.items() if n > 0]
    return _bucket_upper(max(live)) if live else None


def count_over_threshold(buckets: Dict[int, int], threshold_s: float) -> int:
    """Observations whose bucket midpoint exceeds ``threshold_s`` (the
    SLO plane's bad-event count: bucket-resolution exact)."""
    return sum(n for idx, n in buckets.items()
               if _bucket_mid(idx) > threshold_s)


# ---------------------------------------------------------------------
# ledger state
# ---------------------------------------------------------------------

_lock = threading.Lock()
_enabled = True

_open: Dict[str, float] = {}                   # uid -> arrival perf ts
_epoch_stages: Dict[int, Dict[str, float]] = {}
_hists: Dict[Tuple[str, str, str], StreamHist] = {}
_stage_hists: Dict[Tuple[str, str], StreamHist] = {}
_sub_hist = StreamHist()                       # sub-cycle arrival->decision
_admission_hists: Dict[Tuple[str, str], StreamHist] = {}

_closed_total = 0
_deferred_closed_total = 0
_unmatched_total = 0
_evicted_total = 0

_retained: Optional[deque] = None              # audit mode (chaos/tests)

_TLS = threading.local()


def set_enabled(on: bool) -> None:
    """The A/B toggle: OFF stops stamping and closing entirely (the
    dryrun proves readback accounting is identical either way)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Clear all ledger state (tests / soak isolation). Histograms are
    process-lifetime accumulators otherwise, like everything in
    metrics.py."""
    global _sub_hist, _closed_total, _deferred_closed_total
    global _unmatched_total, _evicted_total, _retained
    with _lock:
        _open.clear()
        _epoch_stages.clear()
        _hists.clear()
        _stage_hists.clear()
        _admission_hists.clear()
        _sub_hist = StreamHist()
        _closed_total = 0
        _deferred_closed_total = 0
        _unmatched_total = 0
        _evicted_total = 0
        _retained = None


# ---------------------------------------------------------------------
# stamps
# ---------------------------------------------------------------------

def stamp_arrival(pod) -> None:
    """Stamp a PENDING pod's arrival (first stamp wins — a pod can
    re-enter via update_pod without resetting its clock)."""
    if not _enabled:
        return
    global _evicted_total
    t = _now()
    uid = pod.uid
    with _lock:
        if uid in _open:
            return
        if len(_open) >= MAX_OPEN:
            _open.pop(next(iter(_open)))
            _evicted_total += 1
        _open[uid] = t


def discard(uid: str) -> None:
    """Drop an open record (pod deleted while pending — no decision will
    ever close it)."""
    with _lock:
        _open.pop(uid, None)


def _epoch_map(epoch: int) -> Dict[str, float]:
    st = _epoch_stages.get(epoch)
    if st is None:
        st = _epoch_stages[epoch] = {}
        while len(_epoch_stages) > EPOCH_KEEP:
            _epoch_stages.pop(min(_epoch_stages))
    return st


def stage_mark(stage: str, epoch: Optional[int] = None) -> None:
    """Stamp a stage completion/entry time into a cycle epoch's map
    (``cache.bind_many`` marks "apply" at entry; span exits mark the
    rest via :func:`on_span_exit`)."""
    if not _enabled:
        return
    if epoch is None:
        from . import spans as _spans
        epoch = _spans.current_epoch()
    if epoch is None:
        return
    t = _now()
    with _lock:
        _epoch_map(epoch)[stage] = t


#: span-exit -> stage mapping: the fold phase, the tensorize (pack)
#: phase and any kernel dispatch mark their epoch's stage completion
_PHASE_STAGES = {"fold": "fold", "tensorize": "pack"}


def on_span_exit(sp) -> None:
    """Registered in spans.SPAN_HOOKS at obs import. Must stay cheap —
    it runs at EVERY span exit (the test_obs per-span budget covers it):
    two attribute reads and a dict probe on the miss path."""
    if not _enabled:
        return
    cat = sp.cat
    if cat == "kernel":
        stage = "solve"
    elif cat == "phase":
        stage = _PHASE_STAGES.get(sp.name)
        if stage is None:
            return
    else:
        return
    from . import spans as _spans
    epoch = _spans.current_epoch()
    if epoch is None:
        return
    with _lock:
        _epoch_map(epoch)[stage] = sp.t0 + sp.dur


# ---------------------------------------------------------------------
# deferred attribution (the pipelined consume path)
# ---------------------------------------------------------------------

class _Attr:
    __slots__ = ("epoch", "deferred")

    def __init__(self, epoch: Optional[int], deferred: bool):
        self.epoch = epoch
        self.deferred = deferred


class attribute:
    """``with ledger.attribute(epoch=k, deferred=True):`` — closes inside
    the block take fold/pack/solve stamps from epoch ``k`` (the LAUNCHING
    cycle) and are flagged deferred; apply still comes from the current
    (consuming) epoch. The pipelined executor wraps replay_decisions in
    this so the overlap window is attributed honestly."""

    __slots__ = ("_attr", "_prev")

    def __init__(self, epoch: Optional[int], deferred: bool = True):
        self._attr = _Attr(epoch, deferred)
        self._prev = None

    def __enter__(self) -> "attribute":
        self._prev = getattr(_TLS, "attr", None)
        _TLS.attr = self._attr
        return self

    def __exit__(self, *exc) -> None:
        _TLS.attr = self._prev


# ---------------------------------------------------------------------
# close (the bind funnel)
# ---------------------------------------------------------------------

def _hist_for(key: Tuple[str, str, str]) -> StreamHist:
    h = _hists.get(key)
    if h is None:
        if len(_hists) >= MAX_KEYS:
            key = _OVERFLOW_KEY
            h = _hists.get(key)
            if h is None:
                h = _hists[key] = StreamHist()
        else:
            h = _hists[key] = StreamHist()
    return h


def _stage_hist_for(lane: str, stage: str) -> StreamHist:
    key = (lane, stage)
    h = _stage_hists.get(key)
    if h is None:
        if len(_stage_hists) >= MAX_KEYS:
            key = ("other", stage)
            h = _stage_hists.get(key)
            if h is None:
                h = _stage_hists[key] = StreamHist()
        else:
            h = _stage_hists[key] = StreamHist()
    return h


def _current_engine() -> str:
    try:       # lazy + guarded: obs must not hard-depend on actions
        from ..actions import allocate as _alloc
        return getattr(_alloc, "last_cycle_engine", "") or "none"
    except Exception:                      # pragma: no cover
        return "none"


def close(pod, engine: Optional[str] = None) -> None:
    """Close a pod's record at its decision apply (the cache bind
    funnel: ``bind``/``bind_many`` state flip — full cycle, sub-cycle
    and pipelined replay all pass through there). Unmatched closes (no
    arrival stamp — the ledger was enabled mid-flight) are counted, not
    invented."""
    if not _enabled:
        return
    global _closed_total, _deferred_closed_total, _unmatched_total
    t = _now()
    uid = pod.uid
    attr: Optional[_Attr] = getattr(_TLS, "attr", None)
    from . import spans as _spans
    cur_epoch = _spans.current_epoch()
    solve_epoch = attr.epoch if attr is not None else cur_epoch
    deferred = bool(attr.deferred) if attr is not None else False
    lane = (pod.annotations or {}).get(LANE_ANNOTATION, DEFAULT_LANE)
    tenant = pod.namespace or "default"
    eng = engine or _current_engine()
    with _lock:
        arrival = _open.pop(uid, None)
        if arrival is None:
            _unmatched_total += 1
            return
        src = _epoch_stages.get(solve_epoch, {}) if solve_epoch else {}
        cur = (_epoch_stages.get(cur_epoch, {})
               if cur_epoch and cur_epoch != solve_epoch else src)
        # monotone stage walk: drop stamps older than the pod's arrival,
        # never let a stage precede the one before it
        stages: List[Tuple[str, float]] = []
        ts = arrival
        for stage in STAGES:
            v = cur.get("apply") if stage == "apply" else src.get(stage)
            if v is None and stage == "apply":
                v = src.get("apply")
            if v is not None and v >= ts:
                stages.append((stage, v))
                ts = v
        bind_ts = max(t, ts)
        total = bind_ts - arrival
        _hist_for((lane, tenant, eng)).observe(total)
        prev = arrival
        for stage, v in stages:
            _stage_hist_for(lane, stage).observe(v - prev)
            prev = v
        _stage_hist_for(lane, "bind").observe(bind_ts - prev)
        _closed_total += 1
        if deferred:
            _deferred_closed_total += 1
        if _retained is not None:
            _retained.append({
                "uid": uid, "name": getattr(pod, "name", ""),
                "lane": lane, "tenant": tenant, "engine": eng,
                "epoch": solve_epoch, "deferred": deferred,
                "arrival": arrival, "stages": stages, "bind": bind_ts,
                "total_ms": round(total * 1e3, 6),
            })


# ---------------------------------------------------------------------
# sub-cycle arrival + admission feeds
# ---------------------------------------------------------------------

def observe_subcycle_arrival(seconds: float) -> None:
    """The sub-cycle arrival->decision feed: metrics.observe_arrival_
    latency routes here; the exact arrival COUNT stays in metrics (the
    ledger histogram carries the shape)."""
    if not _enabled:
        return
    with _lock:
        _sub_hist.observe(seconds)


def subcycle_percentiles() -> Optional[dict]:
    """p50/p99 ms of the sub-cycle arrival latencies, or None before the
    first observation — what arrival_latency_percentiles() serves."""
    with _lock:
        if not _sub_hist.count:
            return None
        counts = dict(_sub_hist.buckets)
        n = _sub_hist.count
    p50 = _pct_from_counts(counts, 50.0)
    p99 = _pct_from_counts(counts, 99.0)
    return {"count": n,
            "p50_ms": round((p50 or 0.0) * 1e3, 3),
            "p99_ms": round((p99 or 0.0) * 1e3, 3)}


def observe_admission(tenant: str, lane: str, wait_seconds: float) -> None:
    """Per-(tenant, lane) admission-queue wait (tenantsvc WFQ pull):
    the lane attribution the shared-sidecar latency story needs."""
    if not _enabled:
        return
    with _lock:
        key = (tenant, lane)
        h = _admission_hists.get(key)
        if h is None:
            if len(_admission_hists) >= MAX_KEYS:
                key = ("other", lane)
                h = _admission_hists.setdefault(key, StreamHist())
            else:
                h = _admission_hists[key] = StreamHist()
        h.observe(wait_seconds)


# ---------------------------------------------------------------------
# reads
# ---------------------------------------------------------------------

def _merge(snaps: Iterable[Tuple[int, float, Dict[int, int]]]
           ) -> Tuple[int, float, Dict[int, int]]:
    total, s, merged = 0, 0.0, {}
    for n, hsum, buckets in snaps:
        total += n
        s += hsum
        for idx, c in buckets.items():
            merged[idx] = merged.get(idx, 0) + c
    return total, s, merged


def _select(base: Dict[Tuple[str, str, str],
                       Tuple[int, float, Dict[int, int]]],
            lane: Optional[str], tenant: Optional[str],
            engine: Optional[str]):
    for (ln, tn, en), snap in base.items():
        if lane is not None and ln != lane:
            continue
        if tenant is not None and tn != tenant:
            continue
        if engine is not None and en != engine:
            continue
        yield snap


class LedgerWindow:
    """A point-in-time baseline; reads diff LIVE ledger state against
    it, so bench measures exactly its window — the replacement for the
    deleted hand-rolled percentile code."""

    def __init__(self) -> None:
        with _lock:
            self._base = {k: h.snapshot() for k, h in _hists.items()}
            self._sub_base = _sub_hist.snapshot()
            self._closed0 = _closed_total
            self._deferred0 = _deferred_closed_total

    def _diffs(self, lane=None, tenant=None, engine=None
               ) -> Tuple[int, float, Dict[int, int]]:
        with _lock:
            live = {k: h.snapshot() for k, h in _hists.items()}
        out = []
        for key, (n, s, buckets) in live.items():
            bn, bs, bb = self._base.get(key, (0, 0.0, {}))
            d = {i: c - bb.get(i, 0) for i, c in buckets.items()
                 if c - bb.get(i, 0) > 0}
            out.append((n - bn, s - bs, d))
        return _merge(_select(
            {k: v for k, v in zip(live.keys(), out)},
            lane, tenant, engine))

    def count(self, lane=None, tenant=None, engine=None) -> int:
        return self._diffs(lane, tenant, engine)[0]

    def percentile(self, p: float, lane=None, tenant=None, engine=None
                   ) -> Optional[float]:
        """Window percentile in MILLISECONDS, or None on an empty
        window."""
        _, _, merged = self._diffs(lane, tenant, engine)
        v = _pct_from_counts(merged, p)
        return None if v is None else v * 1e3

    def mean_ms(self, lane=None, tenant=None, engine=None
                ) -> Optional[float]:
        n, s, _ = self._diffs(lane, tenant, engine)
        return (s / n * 1e3) if n else None

    def max_ms(self, lane=None, tenant=None, engine=None
               ) -> Optional[float]:
        _, _, merged = self._diffs(lane, tenant, engine)
        v = _max_from_counts(merged)
        return None if v is None else v * 1e3

    # -- sub-cycle arrival window (bench --mode arrival) ---------------
    def _sub_diff(self) -> Tuple[int, Dict[int, int]]:
        with _lock:
            n, _, buckets = _sub_hist.snapshot()
        bn, _, bb = self._sub_base
        return (n - bn, {i: c - bb.get(i, 0) for i, c in buckets.items()
                         if c - bb.get(i, 0) > 0})

    def subcycle_count(self) -> int:
        return self._sub_diff()[0]

    def subcycle_percentile(self, p: float) -> Optional[float]:
        v = _pct_from_counts(self._sub_diff()[1], p)
        return None if v is None else v * 1e3

    def subcycle_max_ms(self) -> Optional[float]:
        v = _max_from_counts(self._sub_diff()[1])
        return None if v is None else v * 1e3

    def closed(self) -> int:
        return _closed_total - self._closed0

    def deferred_closed(self) -> int:
        return _deferred_closed_total - self._deferred0


def window() -> LedgerWindow:
    return LedgerWindow()


def percentile(p: float, lane=None, tenant=None, engine=None
               ) -> Optional[float]:
    """Process-lifetime percentile in ms (no window) — /debug surfaces."""
    with _lock:
        snaps = list(_select({k: h.snapshot() for k, h in _hists.items()},
                             lane, tenant, engine))
    _, _, merged = _merge(snaps)
    v = _pct_from_counts(merged, p)
    return None if v is None else v * 1e3


def stats() -> dict:
    """The ledger section of metrics.counters_snapshot() — counters plus
    compact per-lane arrival->bind percentiles."""
    with _lock:
        lanes: Dict[str, List] = {}
        for (lane, _, _), h in _hists.items():
            lanes.setdefault(lane, []).append(h.snapshot())
        open_n = len(_open)
        closed = _closed_total
        deferred = _deferred_closed_total
        unmatched = _unmatched_total
        evicted = _evicted_total
        keys = len(_hists)
    per_lane = {}
    for lane, snaps in sorted(lanes.items()):
        n, _, merged = _merge(snaps)
        if not n:
            continue
        per_lane[lane] = {
            "count": n,
            "p50_ms": round((_pct_from_counts(merged, 50.0) or 0.0) * 1e3,
                            3),
            "p99_ms": round((_pct_from_counts(merged, 99.0) or 0.0) * 1e3,
                            3),
        }
    out = {
        "enabled": _enabled,
        "closed_total": closed,
        "deferred_closed_total": deferred,
        "unmatched_total": unmatched,
        "evicted_total": evicted,
        "open": open_n,
        "keys": keys,
    }
    if per_lane:
        out["arrival_bind"] = per_lane
    sub = subcycle_percentiles()
    if sub:
        out["subcycle_arrival"] = sub
    with _lock:
        adm = {f"{t}/{ln}": h.snapshot()
               for (t, ln), h in _admission_hists.items()}
    if adm:
        out["admission_wait"] = {
            k: {"count": n,
                "p99_ms": round((_pct_from_counts(b, 99.0) or 0.0) * 1e3,
                                3)}
            for k, (n, _, b) in sorted(adm.items())}
    return out


# ---------------------------------------------------------------------
# closed-record retention (the chaos soak's audit mode)
# ---------------------------------------------------------------------

def retain(capacity: int = 65536) -> None:
    """Keep the last ``capacity`` CLOSED records for audit (the chaos
    soak asserts every bound pod closed with monotone stamps). OFF by
    default — production closes into histograms only."""
    global _retained
    with _lock:
        _retained = deque(maxlen=int(capacity))


def stop_retention() -> None:
    global _retained
    with _lock:
        _retained = None


def retained() -> List[dict]:
    with _lock:
        return list(_retained) if _retained is not None else []
