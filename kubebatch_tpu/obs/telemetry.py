"""Host decode of the device telemetry frame (ISSUE 12 tentpole).

The device engines append a fixed [TELEM_WIDTH] int32 frame
(kernels/telemetry.py) to the packed block they already ship back in
the cycle's ONE blocking readback. This module is the host side:
decode the words, remember the last frame per engine, attach the
decoded dict to the dispatch span (so it shows in Chrome-trace args
and flight-recorder dumps, and — for sidecar solves — crosses the rpc
hop inside the existing kb-trace-bin trailing metadata), and fold it
into metrics.py's gauges/histograms and the readbacks-per-decision
accounting.

decode/record ALWAYS run — 16 host ints per dispatch, no device work —
so readback and decision accounting are identical whether span
retention is on or off (obs.set_enabled only gates tree attachment;
with retention off the thread stack is empty and the span attach is a
no-op).
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from .. import metrics
from ..kernels.telemetry import ENGINE_NAMES, FIELDS, TELEM_WIDTH
from . import spans as _spans

__all__ = ["TELEM_WIDTH", "FIELDS", "decode", "record", "last_frame",
           "last_frames"]

_lock = threading.Lock()
_last: dict = {}


def decode(words) -> dict:
    """[TELEM_WIDTH] int32 words -> {field: int}, with the engine id
    resolved to its name. Tolerates longer inputs (callers may pass an
    unsliced tail)."""
    w = np.asarray(words).reshape(-1)[:TELEM_WIDTH]
    frame = {name: int(w[i]) for i, name in enumerate(FIELDS)}
    frame["engine"] = ENGINE_NAMES.get(frame["engine"],
                                       str(frame["engine"]))
    return frame


def record(words, span=None, tenant: Optional[str] = None) -> dict:
    """Decode one frame and publish it everywhere it is consumed:

    - the last-frame store (flight recorder ring entries, dryrun,
      tests);
    - the dispatch span's args (Chrome trace + rpc trailing metadata):
      ``span`` explicit, else the innermost open span on this thread;
    - metrics.observe_telemetry (per-engine gauges, bounded histograms,
      and the decisions accumulator readbacks-per-decision divides by).

    Called at the readback decode site with a slice of the ALREADY
    transferred host array — it must never touch device memory (the
    one-blocking-readback pin counts transfers, not decodes)."""
    frame = decode(words)
    eng = frame["engine"]
    with _lock:
        _last[eng] = frame
    if span is None:
        st = getattr(_spans._TLS, "stack", None)
        span = st[-1] if st else None
    if span is not None:
        span.args = dict(span.args or {}, telemetry=frame)
    metrics.observe_telemetry(eng, frame, tenant=tenant)
    return frame


def last_frame(engine: str) -> Optional[dict]:
    """Most recent decoded frame for ``engine``, or None."""
    with _lock:
        return _last.get(engine)


def last_frames() -> dict:
    """Copy of the last decoded frame per engine (each flight-recorder
    ring entry embeds this — a demotion dump shows what the device saw
    on the failing cycle)."""
    with _lock:
        return dict(_last)


def _cycle_hook(root) -> None:
    # cycle wall time into the bounded histogram rendered at /metrics
    metrics.observe_cycle_latency_ms(root.dur * 1e3)


_spans.CYCLE_HOOKS.append(_cycle_hook)
