"""Flight recorder — the last K cycles, self-contained, dumped on failure.

A mid-soak failure used to mean log archaeology: the chaos ladder
demotes engines on signals that only exist as interleaved log lines, and
by the time a human looks, the cycles that mattered are gone. The
recorder keeps a bounded ring of per-cycle records — the full span tree,
a counter snapshot (every process-lifetime mirror counter in
metrics.counters_snapshot), and the degradation-ladder state — and
auto-dumps the ring to disk when something goes wrong:

- a ``cycle_failures_total`` increment (the scheduler's guarded cycle
  counted an exception / deadline overrun / recompile overrun);
- a degradation-ladder demotion (faults.py notifies via
  ``on_ladder_demotion``);
- a chaos-soak invariant violation (sim/chaos.py calls ``dump``).

Recording is a cycle hook (obs.spans.CYCLE_HOOKS) and only runs while
ARMED — the steady hot path pays nothing when the recorder is off. Arm
via the CLI ``--flight-record[=DIR]``, ``KUBEBATCH_FLIGHT_RECORD``, or
``arm()`` in tests. Each dump is one JSON file:

    <dir>/flightrec-<seq>-<reason>.json
    { "reason": ..., "ts": ...,
      "cycles": [ {spans, counters, ladder, telemetry}... ] }

so the artifact answers "what did the last K cycles look like, and what
were the counters at each of them" without any other file.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .. import metrics
from .spans import CYCLE_HOOKS, Span

log = logging.getLogger("kubebatch.obs")

__all__ = ["FlightRecorder", "RECORDER", "arm", "disarm", "armed",
           "record_cycle", "dump", "maybe_dump_on_failure"]

#: default ring depth: enough cycles to cover a demote->probe->re-trip
#: sequence at the chaos policy's cadence, small enough that a dump is
#: a few hundred KB
DEFAULT_CAPACITY = 16

#: cap on dumps per process — a crash-looping scheduler must fill disks
#: with cycles, not dumps
MAX_DUMPS = 64


def _ladder_state() -> dict:
    from .. import faults
    lad = faults.LADDER
    return {
        "level": lad.level,
        "level_name": faults.LADDER_LEVELS[lad.level],
        "demote_after": lad.demote_after,
        "promote_after": lad.promote_after,
        "armed_plan": (dict(faults.active_plan().injected)
                       if faults.active_plan() is not None else None),
    }


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self.directory: Optional[str] = None
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps: List[str] = []
        #: cycle_failures_total at the last record/dump check — the
        #: failure trigger fires on the DELTA, not the absolute count
        self._failures_seen = metrics.cycle_failures_total()

    # ---- recording ----------------------------------------------------
    def record_cycle(self, root: Span) -> None:
        """Cycle hook: ring-buffer one record. Cheap — one to_dict walk
        of a tree with tens of nodes plus dict copies of the counters;
        the rpc percentile pass is skipped per cycle (the dump header
        computes it once at dump time)."""
        from . import telemetry
        rec = {
            "ts": time.time(),
            "spans": root.to_dict(),
            "counters": metrics.counters_snapshot(include_rpc=False),
            "ladder": _ladder_state(),
            # last decoded device telemetry frame per engine — the
            # kernel's own account of the cycle, alongside the host view
            "telemetry": telemetry.last_frames(),
        }
        with self._lock:
            self._ring.append(rec)

    # ---- dumping ------------------------------------------------------
    def dump(self, reason: str) -> Optional[str]:
        """Write the ring to disk; returns the path (None if unarmed,
        empty, or over the dump cap)."""
        with self._lock:
            if self.directory is None or not self._ring:
                return None
            if len(self.dumps) >= MAX_DUMPS:
                return None
            self._seq += 1
            seq = self._seq
            cycles = list(self._ring)
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in reason)[:80]
        path = os.path.join(self.directory,
                            f"flightrec-{seq:04d}-{safe}.json")
        doc = {"reason": reason, "ts": time.time(),
               "ladder": _ladder_state(),
               "counters": metrics.counters_snapshot(),
               "cycles": cycles}
        try:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except Exception:
            log.exception("flight-recorder dump failed (%s)", reason)
            return None
        with self._lock:
            self.dumps.append(path)
        log.warning("flight recorder dumped %d cycles to %s (%s)",
                    len(cycles), path, reason)
        return path

    def maybe_dump_on_failure(self, reason: Optional[str] = None
                              ) -> Optional[str]:
        """Dump iff cycle_failures_total advanced since the last check
        (the scheduler calls this after every guarded failure path,
        passing the failing cycle's actual reason so the artifact is
        named after THIS failure, not the historically dominant one)."""
        total = metrics.cycle_failures_total()
        if total <= self._failures_seen:
            return None
        self._failures_seen = total
        return self.dump(f"cycle_failure-{reason or 'failure'}")

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dumps.clear()
            self._failures_seen = metrics.cycle_failures_total()


RECORDER = FlightRecorder()


def _on_cycle(root: Span) -> None:
    RECORDER.record_cycle(root)


def _on_demotion(level: int) -> None:
    RECORDER.dump(f"ladder_demotion-level{level}")


def arm(directory: str, capacity: int = DEFAULT_CAPACITY) -> FlightRecorder:
    """Arm the process-wide recorder: record every cycle into a ring of
    ``capacity`` and auto-dump to ``directory`` on the trigger set."""
    from .. import faults
    os.makedirs(directory, exist_ok=True)
    RECORDER.directory = directory
    if capacity != RECORDER.capacity:
        RECORDER.capacity = capacity
        with RECORDER._lock:
            RECORDER._ring = deque(RECORDER._ring, maxlen=capacity)
    RECORDER._failures_seen = metrics.cycle_failures_total()
    if _on_cycle not in CYCLE_HOOKS:
        CYCLE_HOOKS.append(_on_cycle)
    faults.on_ladder_demotion(_on_demotion)
    log.warning("flight recorder ARMED (dir=%s, last %d cycles)",
                directory, capacity)
    return RECORDER


def disarm() -> None:
    from .. import faults
    RECORDER.directory = None
    RECORDER.reset()
    try:
        CYCLE_HOOKS.remove(_on_cycle)
    except ValueError:
        pass
    faults.remove_ladder_demotion_hook(_on_demotion)


def armed() -> bool:
    return RECORDER.directory is not None


def record_cycle(root: Span) -> None:
    RECORDER.record_cycle(root)


def dump(reason: str) -> Optional[str]:
    return RECORDER.dump(reason)


def maybe_dump_on_failure(reason: Optional[str] = None) -> Optional[str]:
    return RECORDER.maybe_dump_on_failure(reason)


def arm_from_env(env: str = "KUBEBATCH_FLIGHT_RECORD") -> Optional[str]:
    """Daemon path: arm from the environment (value = dump dir; "1"/"" ->
    a default under the cwd)."""
    val = os.environ.get(env)
    if not val:
        return None
    directory = val if val not in ("1", "true") else "flight-records"
    arm(directory)
    return directory
