"""Debug/metrics HTTP endpoint — scrapeable even without prometheus_client.

The reference serves /metrics through promhttp and nothing else; a
production scheduler needs liveness and debug surfaces too, and they
must not disappear just because the prometheus client library is absent
(the mirror counters in metrics.py are the source of truth either way).
One small stdlib ThreadingHTTPServer serves:

- ``/metrics``       — the Prometheus registry when prometheus_client is
  importable, else an OpenMetrics exposition of the mirror counters
  (typed ``# HELP``/``# TYPE`` lines, histogram buckets, ``# EOF``) so
  scrapers ingest the fallback correctly too — the surface never 404s;
- ``/healthz``       — liveness JSON: status "ok" at the full engine,
  "degraded" under any ladder demotion, "failing" when the ladder is
  pinned at its floor; plus ladder level, cycle failure count,
  spans/cycle;
- ``/debug/vars``    — every process-lifetime mirror counter
  (metrics.counters_snapshot) as one JSON document: demotions, faults,
  compile/recompile, host phases, readbacks, rpc dispatch percentiles,
  tracer stats;
- ``/debug/explain`` — the latest unschedulability-explainer snapshot
  (obs/explain.py), or ``{"enabled": false}`` when it never ran.

Replaces the bare prometheus ``start_http_server`` call in runtime/cli.py.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from .. import metrics

__all__ = ["DebugHTTPServer", "start"]


#: leaf keys that are monotone accumulators despite lacking the
#: ``_total`` suffix (the suffix rule covers everything else)
_COUNTER_LEAVES = {"blocking_readbacks", "readbacks", "decisions",
                   "dispatches", "count"}

#: OpenMetrics media type (the ``# EOF`` terminator below is part of it)
OPENMETRICS_CTYPE = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8")


def _render_openmetrics(snapshot: dict) -> str:
    """OpenMetrics exposition of the mirror counters — the /metrics
    fallback without prometheus_client. Typing derives from the
    snapshot's structure: ``*_total`` names (and the readback/decision
    accumulators) are counters, dicts shaped like
    metrics._BoundedHist.snapshot() render as full histograms
    (``_bucket{le=...}``/``_sum``/``_count``), every other numeric leaf
    is a gauge. Nested dict keys flatten into the metric name, so the
    exposition covers exactly what /debug/vars covers."""
    out = []

    def emit(name: str, mtype: str, help_: str, lines) -> None:
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {mtype}")
        out.extend(lines)

    def is_hist(v) -> bool:
        return (isinstance(v, dict) and isinstance(v.get("buckets"), dict)
                and "sum" in v and "count" in v)

    def clean(k: str) -> str:
        return (str(k).replace("-", "_").replace(".", "_")
                .replace("/", "_").replace(" ", "_"))

    def walk(prefix: str, value, leaf_key: str = "") -> None:
        name = f"kube_batch_{prefix}"
        if is_hist(value):
            lines = []
            for ub, cum in value["buckets"].items():
                lines.append(f'{name}_bucket{{le="{float(ub)}"}} {cum}')
            lines.append(f'{name}_bucket{{le="+Inf"}} {value["count"]}')
            lines.append(f"{name}_sum {value['sum']}")
            lines.append(f"{name}_count {value['count']}")
            emit(name, "histogram", f"{leaf_key} (bounded histogram)",
                 lines)
            return
        if isinstance(value, dict):
            for k, v in sorted(value.items()):
                key = clean(k)
                walk(f"{prefix}_{key}" if prefix else key, v, str(k))
            return
        if isinstance(value, bool):
            value = int(value)
        if isinstance(value, (int, float)):
            mtype = ("counter" if (name.endswith("_total")
                                   or leaf_key in _COUNTER_LEAVES)
                     else "gauge")
            emit(name, mtype, leaf_key or prefix, [f"{name} {value}"])

    walk("", snapshot)
    out.append("# EOF")
    return "\n".join(out) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "kubebatch-obs/1"

    def log_message(self, *args) -> None:   # quiet; the scheduler logs
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, obj, code: int = 200) -> None:
        self._send(code, json.dumps(obj, indent=1, default=str).encode(),
                   "application/json")

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                from ..faults import LADDER_LEVELS
                snap = metrics.counters_snapshot()
                level = snap.get("degradation_level", 0)
                # "ok" only at the full engine; any demotion is
                # "degraded", and a ladder pinned at its floor (every
                # engine tier exhausted) is the failing state
                at_floor = level >= len(LADDER_LEVELS) - 1
                from .. import __version__
                self._send_json({
                    "status": ("failing" if at_floor
                               else "degraded" if level else "ok"),
                    "version": __version__,
                    "degradation_level": level,
                    "cycle_failures_total":
                        snap.get("cycle_failures_total", 0),
                    "blocking_readbacks":
                        snap.get("blocking_readbacks", 0),
                    "tracer": snap.get("tracer", {}),
                })
            elif path == "/debug/vars":
                self._send_json(metrics.counters_snapshot())
            elif path == "/debug/explain":
                from . import explain
                snap = explain.latest()
                if snap is None:
                    self._send_json({
                        "enabled": False,
                        "hint": "run with --explain-unschedulable (or "
                                "call obs.explain.explain_session) to "
                                "populate this snapshot",
                    })
                else:
                    self._send_json(snap)
            elif path == "/debug/slo":
                from . import ledger as _ledger
                from . import slo as _slo
                # the SLO plane's live burn rates plus the ledger
                # counters the objectives evaluate over (ISSUE 17)
                payload = _slo.snapshot()
                payload["ledger"] = _ledger.stats()
                self._send_json(payload)
            elif path == "/metrics":
                try:
                    from prometheus_client import (REGISTRY,
                                                   generate_latest)
                    self._send(200, generate_latest(REGISTRY),
                               "text/plain; version=0.0.4")
                except Exception:
                    self._send(200, _render_openmetrics(
                        metrics.counters_snapshot()).encode(),
                        OPENMETRICS_CTYPE)
            else:
                self._send_json({"error": "not found", "endpoints": [
                    "/metrics", "/healthz", "/debug/vars",
                    "/debug/explain", "/debug/slo"]}, code=404)
        except BrokenPipeError:            # pragma: no cover — client gone
            pass
        except Exception as e:             # a debug surface never crashes
            try:
                self._send_json({"error": f"{type(e).__name__}: {e}"},
                                code=500)
            except Exception:              # pragma: no cover
                pass


class DebugHTTPServer:
    """Owns the ThreadingHTTPServer + its daemon thread."""

    def __init__(self, addr: str = "0.0.0.0", port: int = 8080):
        self._httpd = ThreadingHTTPServer((addr, port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DebugHTTPServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="kb-obs-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def start(listen_address: str) -> Optional[DebugHTTPServer]:
    """CLI helper: ':8080' / 'host:port' -> a started server, or None on
    bind failure (the daemon must schedule even when the port is taken)."""
    host, _, port = listen_address.rpartition(":")
    try:
        return DebugHTTPServer(host or "0.0.0.0", int(port)).start()
    except Exception:
        return None
