"""kubebatch_tpu.obs — tracing, flight recording, and explainability.

The observability subsystem (ISSUE 7 / docs/OBSERVABILITY.md):

- :mod:`.spans`   — the span tracer every legacy perf_counter timing
  site routes through; builds the per-cycle span tree (cycle -> action
  -> host phase -> kernel dispatch -> blocking readback) and fires the
  old metric accumulators as derived views at span exit;
- :mod:`.export`  — Chrome trace-event JSON (Perfetto-loadable) export
  of span trees, armed per trace directory;
- :mod:`.flight`  — the bounded flight-recorder ring (span trees +
  counter snapshots + ladder state), auto-dumped on cycle failures,
  ladder demotions and chaos invariant violations;
- :mod:`.explain` — the opt-in unschedulability explainer (one extra
  readback, never on the steady path);
- :mod:`.telemetry` — host decode of the device telemetry frame every
  engine appends to its packed result (rides the one readback);
- :mod:`.ledger`  — the per-pod decision-latency ledger (arrival ->
  fold -> pack -> solve -> apply -> bind) closing into log-bucketed
  streaming histograms keyed (lane, tenant, engine);
- :mod:`.slo`     — declarative latency objectives over the ledger,
  evaluated as multi-window burn rates; breaches fire the flight
  recorder + slo_breaches_total and serve on /debug/slo;
- :mod:`.timeline` — bounded ring of per-cycle digests with JSONL
  spill and an EWMA drift rung (the ≥10k-cycle soak substrate);
- :mod:`.http`    — /metrics, /healthz, /debug/vars, /debug/explain,
  /debug/slo.

Import discipline: this package imports only metrics (and jax, which
every kernel module already pays for); actions/kernels/rpc import obs,
never the reverse at module scope — no cycles. The one exception is
.telemetry's frame-layout import from kernels.telemetry, a leaf module
with no obs dependency; it is imported at the BOTTOM of this file so
the kernels package (whose own modules import obs.span) always finds
this package initialized.
"""
from .spans import (CYCLE_HOOKS, Span, add_event, arm_profile, begin_cycle,
                    begin_server_root, current_cycle, current_epoch, cycle,
                    enabled, end_cycle, end_server_root, graft, last_cycle,
                    now, set_enabled, span, span_overhead_estimate,
                    spans_total, tracer_stats)

__all__ = ["CYCLE_HOOKS", "Span", "add_event", "arm_profile",
           "begin_cycle", "begin_server_root", "current_cycle",
           "current_epoch", "cycle", "enabled", "end_cycle",
           "end_server_root", "graft", "last_cycle", "now", "set_enabled",
           "span", "span_overhead_estimate", "spans_total", "telemetry",
           "tracer_stats"]

from . import telemetry  # noqa: E402  (see import discipline above)
from . import ledger  # noqa: E402  (same discipline: metrics-only deps)
from . import slo  # noqa: E402
from . import timeline  # noqa: E402
from .spans import SPAN_HOOKS  # noqa: E402

# the ledger's stage stamps ride span exits; registered HERE (not at
# ledger import) so a direct `import kubebatch_tpu.obs.ledger` in a
# tool can read histograms without arming the hot-path hook twice
if ledger.on_span_exit not in SPAN_HOOKS:
    SPAN_HOOKS.append(ledger.on_span_exit)

__all__ += ["SPAN_HOOKS", "ledger", "slo", "timeline"]
