"""kubebatch_tpu.obs — tracing, flight recording, and explainability.

The observability subsystem (ISSUE 7 / docs/OBSERVABILITY.md):

- :mod:`.spans`   — the span tracer every legacy perf_counter timing
  site routes through; builds the per-cycle span tree (cycle -> action
  -> host phase -> kernel dispatch -> blocking readback) and fires the
  old metric accumulators as derived views at span exit;
- :mod:`.export`  — Chrome trace-event JSON (Perfetto-loadable) export
  of span trees, armed per trace directory;
- :mod:`.flight`  — the bounded flight-recorder ring (span trees +
  counter snapshots + ladder state), auto-dumped on cycle failures,
  ladder demotions and chaos invariant violations;
- :mod:`.explain` — the opt-in unschedulability explainer (one extra
  readback, never on the steady path);
- :mod:`.telemetry` — host decode of the device telemetry frame every
  engine appends to its packed result (rides the one readback);
- :mod:`.http`    — /metrics, /healthz, /debug/vars, /debug/explain.

Import discipline: this package imports only metrics (and jax, which
every kernel module already pays for); actions/kernels/rpc import obs,
never the reverse at module scope — no cycles. The one exception is
.telemetry's frame-layout import from kernels.telemetry, a leaf module
with no obs dependency; it is imported at the BOTTOM of this file so
the kernels package (whose own modules import obs.span) always finds
this package initialized.
"""
from .spans import (CYCLE_HOOKS, Span, add_event, arm_profile, begin_cycle,
                    begin_server_root, current_cycle, current_epoch, cycle,
                    enabled, end_cycle, end_server_root, graft, last_cycle,
                    now, set_enabled, span, span_overhead_estimate,
                    spans_total, tracer_stats)

__all__ = ["CYCLE_HOOKS", "Span", "add_event", "arm_profile",
           "begin_cycle", "begin_server_root", "current_cycle",
           "current_epoch", "cycle", "enabled", "end_cycle",
           "end_server_root", "graft", "last_cycle", "now", "set_enabled",
           "span", "span_overhead_estimate", "spans_total", "telemetry",
           "tracer_stats"]

from . import telemetry  # noqa: E402  (see import discipline above)
