"""The SLO plane: declarative latency objectives over the decision
ledger, evaluated as multi-window burn rates (ISSUE 17).

An :class:`Objective` names a latency bound and the fraction of
observations that must meet it (e.g. "latency-lane arrival->decision
p99 <= 50 ms" is ``threshold_ms=50, target=0.99``). Evaluation is the
standard multi-window burn-rate scheme: the error budget is
``1 - target``; over a window the burn rate is ``error_rate / budget``,
and a breach fires only when BOTH the fast window (catches the spike)
and the slow window (confirms it is not a blip) burn past the
threshold. Windows diff cumulative (total, bad) counts captured once
per cycle tick — O(1) per tick over the ledger's streaming histograms,
no raw samples anywhere.

A breach fires ONCE per episode (re-arming only after the fast window
recovers): ``metrics.count_slo_breach(objective, window)`` for each
burning window plus one flight-recorder dump — the span trees and
counters of the cycles that blew the budget are exactly what the ring
holds. The ``obs.slo`` fault seam sits in the evaluation tick: a fired
seam forces a synthetic "injected" breach through the SAME pipeline
(counter + flight dump), proving under chaos that the breach path
itself cannot corrupt a cycle — demote-not-raise, like cache.fold.

The plane is armed explicitly (Scheduler ``slo=True`` /
``KUBEBATCH_SLO=1``, bench --mode soak, the chaos soak); disarmed it
costs nothing and ``/debug/slo`` says so. Clocks are injectable so the
burn-rate window math is testable against a synthetic clock.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .. import metrics
from . import ledger as _ledger

__all__ = ["Objective", "DEFAULT_OBJECTIVES", "SLOPlane", "PLANE",
           "arm", "disarm", "armed", "snapshot", "metrics_section"]


@dataclass(frozen=True)
class Objective:
    """One declarative latency objective.

    ``kind`` picks the observation stream: "ledger" = arrival->bind
    records (optionally filtered by ``lane``), "cycle" = scheduler cycle
    durations (fed by the plane's own cycle hook). ``target`` is the
    fraction of observations that must land under ``threshold_ms``
    (0.99 -> a p99 objective; the error budget is 1 - target)."""

    name: str
    kind: str                      # "ledger" | "cycle"
    threshold_ms: float
    target: float
    lane: Optional[str] = None
    fast_s: float = 60.0
    slow_s: float = 600.0
    burn_threshold: float = 1.0
    min_count: int = 8             # a window with fewer obs never fires


#: shipped objectives: the latency-lane arrival->decision p99 bound and
#: a generous cycle-p50 guard (a real deployment overrides thresholds
#: per box; the defaults must never false-fire on a healthy cpu box)
DEFAULT_OBJECTIVES: Tuple[Objective, ...] = (
    Objective(name="latency_arrival_p99", kind="ledger",
              lane=_ledger.LATENCY_LANE, threshold_ms=50.0, target=0.99),
    Objective(name="arrival_decision_p99", kind="ledger",
              threshold_ms=5000.0, target=0.99),
    Objective(name="cycle_p50", kind="cycle",
              threshold_ms=5000.0, target=0.50),
)


class _ObjState:
    __slots__ = ("obj", "snaps", "breached", "breaches")

    def __init__(self, obj: Objective):
        self.obj = obj
        #: (t, total, bad) cumulative snapshots, oldest first; bounded
        #: far past slow_s coverage at one tick per cycle
        self.snaps: deque = deque(maxlen=8192)
        self.breached = False
        self.breaches = 0


class SLOPlane:
    """Owns objective state + the per-cycle evaluation tick. The module
    singleton ``PLANE`` hooks spans.CYCLE_HOOKS when armed; tests build
    their own plane with a synthetic clock and call :meth:`tick`."""

    def __init__(self, objectives=DEFAULT_OBJECTIVES,
                 now: Callable[[], float] = time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self._objs: List[_ObjState] = [_ObjState(o) for o in objectives]
        self._cycle = _ledger.StreamHist()
        self._armed = False
        self._injected = 0

    # -- observation streams ------------------------------------------
    def _totals(self, obj: Objective) -> Tuple[int, int]:
        """Cumulative (total, bad) for an objective's stream."""
        thr_s = obj.threshold_ms / 1e3
        if obj.kind == "cycle":
            return (self._cycle.count,
                    _ledger.count_over_threshold(self._cycle.buckets,
                                                 thr_s))
        total, bad = 0, 0
        for (lane, _, _), h in list(_ledger._hists.items()):
            if obj.lane is not None and lane != obj.lane:
                continue
            n, _, buckets = h.snapshot()
            total += n
            bad += _ledger.count_over_threshold(buckets, thr_s)
        return total, bad

    @staticmethod
    def _window(snaps: deque, t: float, w_s: float,
                total: int, bad: int) -> Tuple[int, int, float]:
        """(d_total, d_bad, covered_s) over the last ``w_s`` seconds —
        diffed against the newest snapshot at or before the window
        start (partial coverage early on uses the oldest)."""
        base_t, base_total, base_bad = t, total, bad
        start = t - w_s
        for st, stotal, sbad in snaps:
            if st <= start:
                base_t, base_total, base_bad = st, stotal, sbad
            else:
                break
        if base_t is t and snaps:       # window predates every snapshot
            base_t, base_total, base_bad = snaps[0]
        return total - base_total, bad - base_bad, t - base_t

    def _burn(self, st: _ObjState, t: float, w_s: float,
              total: int, bad: int) -> dict:
        d_total, d_bad, covered = self._window(st.snaps, t, w_s,
                                               total, bad)
        budget = max(1e-9, 1.0 - st.obj.target)
        rate = (d_bad / d_total) if d_total else 0.0
        return {"seconds": w_s, "covered_s": round(covered, 3),
                "count": d_total, "bad": d_bad,
                "error_rate": round(rate, 6),
                "burn": round(rate / budget, 4),
                "burning": bool(d_total >= st.obj.min_count
                                and rate / budget
                                >= st.obj.burn_threshold)}

    def tick(self, cycle_dur_s: Optional[float] = None,
             t: Optional[float] = None) -> None:
        """One evaluation pass; the cycle hook calls this with the root
        span's duration. Never raises (a broken SLO plane must not fail
        a scheduling cycle)."""
        try:
            self._tick(cycle_dur_s, t)
        except Exception:                  # pragma: no cover
            import logging
            logging.getLogger("kubebatch.obs").exception(
                "slo tick failed")

    def _tick(self, cycle_dur_s, t) -> None:
        from .. import faults
        if t is None:
            t = self._now()
        with self._lock:
            if cycle_dur_s is not None:
                self._cycle.observe(cycle_dur_s)
            if faults.should_fail("obs.slo"):
                # the chaos seam: force a breach through the REAL fire
                # path — counter + flight dump — without any objective
                # actually burning; the soak proves the cycle survives
                self._injected += 1
                self._fire("injected", ("fast", "slow"))
            for st in self._objs:
                total, bad = self._totals(st.obj)
                fast = self._burn(st, t, st.obj.fast_s, total, bad)
                slow = self._burn(st, t, st.obj.slow_s, total, bad)
                if fast["burning"] and slow["burning"]:
                    if not st.breached:    # single-fire per episode
                        st.breached = True
                        st.breaches += 1
                        self._fire(st.obj.name, ("fast", "slow"))
                elif not fast["burning"]:
                    st.breached = False    # fast recovery re-arms
                st.snaps.append((t, total, bad))

    @staticmethod
    def _fire(objective: str, windows) -> None:
        for w in windows:
            metrics.count_slo_breach(objective, w)
        from . import flight as _flight
        _flight.dump(f"slo_breach-{objective}")

    # -- surfaces ------------------------------------------------------
    def snapshot(self) -> dict:
        """The /debug/slo payload."""
        with self._lock:
            t = self._now()
            objs = []
            for st in self._objs:
                total, bad = self._totals(st.obj)
                objs.append({
                    "name": st.obj.name, "kind": st.obj.kind,
                    "lane": st.obj.lane,
                    "threshold_ms": st.obj.threshold_ms,
                    "target": st.obj.target,
                    "windows": {
                        "fast": self._burn(st, t, st.obj.fast_s,
                                           total, bad),
                        "slow": self._burn(st, t, st.obj.slow_s,
                                           total, bad)},
                    "breached": st.breached,
                    "breaches_total": st.breaches,
                })
            return {"armed": self._armed,
                    "injected_total": self._injected,
                    "breaches_total": metrics.slo_breaches_total(),
                    "objectives": objs}

    def metrics_section(self) -> dict:
        """Compact numeric section for counters_snapshot -> OpenMetrics
        gauges (burn rates per objective/window)."""
        with self._lock:
            t = self._now()
            burn: Dict[str, float] = {}
            breached: Dict[str, int] = {}
            for st in self._objs:
                total, bad = self._totals(st.obj)
                burn[f"{st.obj.name}_fast"] = self._burn(
                    st, t, st.obj.fast_s, total, bad)["burn"]
                burn[f"{st.obj.name}_slow"] = self._burn(
                    st, t, st.obj.slow_s, total, bad)["burn"]
                breached[st.obj.name] = int(st.breached)
            return {"armed": int(self._armed), "burn_rate": burn,
                    "breached": breached,
                    "injected_total": self._injected}


PLANE = SLOPlane()


def _on_cycle(root) -> None:
    PLANE.tick(root.dur)


def arm(objectives=None) -> SLOPlane:
    """Arm the module plane (fresh objective state) and hook cycle
    ends. Idempotent re-arm resets window state."""
    global PLANE
    from . import spans as _spans
    disarm()
    PLANE = SLOPlane(objectives or DEFAULT_OBJECTIVES)
    PLANE._armed = True
    _spans.CYCLE_HOOKS.append(_on_cycle)
    return PLANE


def disarm() -> None:
    from . import spans as _spans
    PLANE._armed = False
    while _on_cycle in _spans.CYCLE_HOOKS:
        _spans.CYCLE_HOOKS.remove(_on_cycle)


def armed() -> bool:
    return PLANE._armed


def snapshot() -> dict:
    return PLANE.snapshot()


def metrics_section() -> Optional[dict]:
    """None when disarmed (counters_snapshot stays quiet)."""
    return PLANE.metrics_section() if PLANE._armed else None
