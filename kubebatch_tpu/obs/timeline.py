"""The long-horizon timeline: O(1)-memory per-cycle digests for
multi-hour soaks, with JSONL spill and an EWMA drift rung (ISSUE 17).

A ≥10k-cycle soak needs a replayable record of what every cycle did
WITHOUT retaining 10k span trees. Armed, the timeline hooks cycle ends
and keeps a bounded ring of per-cycle digests — epoch, cycle wall,
span count, COUNTER DELTAS (decisions, blocking/deferred readbacks,
recompiles, cycle failures, ledger closes), current RSS, and a compact
telemetry-frame summary — spilling them append-only to
``<dir>/timeline.jsonl`` every ``spill_every`` digests, so the full
run replays from disk while resident memory stays flat at the ring
bound.

The drift rung is the "instead of silently degrading" half: fast/slow
EWMAs over cycle wall and RSS; when the fast track runs persistently
above the slow one (``DRIFT_PATIENCE`` consecutive ticks past the
tolerance, after a warm-up), the timeline fires ONCE per episode —
``metrics.count_timeline_drift(kind)`` plus a flight-recorder dump —
and the soak gate (bench --mode soak, tools/bench_regression.py) turns
that counter into a hard failure. A leak or a slow latency rot in hour
three becomes a counted, dumped event, not a surprise OOM.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from .. import metrics

__all__ = ["Timeline", "TIMELINE", "arm", "disarm", "armed", "flush",
           "stats", "recent", "MIN_TICKS", "DRIFT_PATIENCE"]

#: EWMA smoothing factors (per-cycle): the fast track reacts within a
#: few dozen cycles, the slow one is the multi-hour baseline
FAST_ALPHA = 0.08
SLOW_ALPHA = 0.005

#: drift tolerances: fast must exceed slow by this fraction
DUR_TOL = 1.5                      # cycle wall: +150% sustained
RSS_TOL = 0.25                     # resident set: +25% sustained

#: ticks before the rung may fire (EWMAs must converge first) and
#: consecutive over-tolerance ticks required (a blip never fires)
MIN_TICKS = 64
DRIFT_PATIENCE = 16

_PAGE = 4096
try:
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):  # pragma: no cover
    pass


def _rss_mb() -> float:
    """Current resident set in MB (|/proc| on linux, peak-RSS fallback
    elsewhere) — cheap enough for once per cycle."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE / 1e6
    except Exception:                  # pragma: no cover — non-linux
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e3


class _Ewma:
    __slots__ = ("fast", "slow", "over")

    def __init__(self) -> None:
        self.fast: Optional[float] = None
        self.slow: Optional[float] = None
        self.over = 0

    def update(self, v: float) -> None:
        self.fast = (v if self.fast is None
                     else self.fast + FAST_ALPHA * (v - self.fast))
        self.slow = (v if self.slow is None
                     else self.slow + SLOW_ALPHA * (v - self.slow))

    def drifting(self, tol: float) -> bool:
        if self.slow is None or self.slow <= 0:
            return False
        return self.fast > self.slow * (1.0 + tol)


class Timeline:
    """Owns the ring, the spill file and the drift state. The module
    singleton ``TIMELINE`` is what arm()/the cycle hook use; tests build
    their own with a synthetic clock."""

    def __init__(self, now: Callable[[], float] = time.monotonic):
        self._now = now
        self._lock = threading.Lock()
        self._armed = False
        self._dir: Optional[str] = None
        self._ring: deque = deque(maxlen=2048)
        self._pending: List[dict] = []
        self._spill_every = 256
        self._ticks = 0
        self._spilled = 0
        self._dur = _Ewma()
        self._rss = _Ewma()
        self._drift_fired = {"cycle_ms": False, "rss_mb": False}
        self._prev: Optional[dict] = None

    def arm(self, directory: Optional[str] = None, capacity: int = 2048,
            spill_every: int = 256) -> "Timeline":
        with self._lock:
            self._armed = True
            self._dir = directory
            if directory:
                os.makedirs(directory, exist_ok=True)
            self._ring = deque(maxlen=int(capacity))
            self._pending = []
            self._spill_every = max(1, int(spill_every))
            self._ticks = 0
            self._spilled = 0
            self._dur = _Ewma()
            self._rss = _Ewma()
            self._drift_fired = {"cycle_ms": False, "rss_mb": False}
            self._prev = None
        return self

    def disarm(self) -> None:
        self.flush()
        with self._lock:
            self._armed = False

    @property
    def path(self) -> Optional[str]:
        return (os.path.join(self._dir, "timeline.jsonl")
                if self._dir else None)

    # -- the per-cycle tick --------------------------------------------
    def _counter_sample(self) -> dict:
        acct = metrics.readback_accounting()
        sample = {
            "decisions": acct.get("decisions", 0),
            "blocking_readbacks": acct.get("readbacks", 0),
            "deferred_readbacks": metrics.deferred_readbacks(),
            "recompiles": metrics.recompiles_total(),
            "cycle_failures": metrics.cycle_failures_total(),
            "subcycles": metrics.subcycles_total(),
        }
        try:
            from . import ledger as _ledger
            sample["ledger_closed"] = _ledger.stats()["closed_total"]
        except Exception:              # pragma: no cover
            sample["ledger_closed"] = 0
        return sample

    @staticmethod
    def _telemetry_summary() -> Optional[dict]:
        try:
            from . import telemetry as _telemetry
            frames = _telemetry.last_frames()
        except Exception:
            return None
        if not frames:
            return None
        out = {}
        for engine, frame in list(frames.items())[:8]:
            if isinstance(frame, dict):
                out[str(engine)] = {
                    k: frame[k] for k in ("waves", "bound", "failed")
                    if k in frame}
            else:                      # pragma: no cover — defensive
                out[str(engine)] = {}
        return out or None

    def tick(self, root) -> None:
        """One digest from a finished cycle root. Never raises."""
        try:
            self._tick(root)
        except Exception:              # pragma: no cover
            import logging
            logging.getLogger("kubebatch.obs").exception(
                "timeline tick failed")

    def _tick(self, root) -> None:
        with self._lock:
            if not self._armed:
                return
            cycle_ms = root.dur * 1e3
            rss = _rss_mb()
            sample = self._counter_sample()
            prev = self._prev or sample
            digest = {
                "ts": round(self._now(), 3),
                "epoch": (root.args or {}).get("epoch"),
                "name": root.name,
                "cycle_ms": round(cycle_ms, 3),
                "spans": root.count(),
                "rss_mb": round(rss, 2),
                "deltas": {k: sample[k] - prev.get(k, 0)
                           for k in sample},
            }
            telem = self._telemetry_summary()
            if telem:
                digest["telemetry"] = telem
            self._prev = sample
            self._ring.append(digest)
            self._pending.append(digest)
            self._ticks += 1
            # ---- drift rung ------------------------------------------
            self._dur.update(cycle_ms)
            self._rss.update(rss)
            if self._ticks >= MIN_TICKS:
                self._drift("cycle_ms", self._dur, DUR_TOL)
                self._drift("rss_mb", self._rss, RSS_TOL)
            if len(self._pending) >= self._spill_every:
                self._spill_locked()

    def _drift(self, kind: str, ewma: _Ewma, tol: float) -> None:
        if ewma.drifting(tol):
            ewma.over += 1
            if (ewma.over >= DRIFT_PATIENCE
                    and not self._drift_fired[kind]):
                # once per episode: count it, dump the flight ring —
                # the alternative is silently degrading for hours
                self._drift_fired[kind] = True
                metrics.count_timeline_drift(kind)
                from . import flight as _flight
                _flight.dump(f"timeline_drift-{kind}")
        else:
            ewma.over = 0
            self._drift_fired[kind] = False

    # -- spill ---------------------------------------------------------
    def _spill_locked(self) -> None:
        pending, self._pending = self._pending, []
        if not self._dir:
            return                     # ring-only mode still bounds
        try:
            with open(self.path, "a") as f:
                for d in pending:
                    f.write(json.dumps(d, separators=(",", ":")) + "\n")
            self._spilled += len(pending)
        except OSError:                # pragma: no cover — disk gone
            import logging
            logging.getLogger("kubebatch.obs").exception(
                "timeline spill failed")

    def flush(self) -> None:
        with self._lock:
            self._spill_locked()

    # -- surfaces ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "armed": int(self._armed),
                "ticks": self._ticks,
                "ring": len(self._ring),
                "ring_capacity": self._ring.maxlen,
                "spilled": self._spilled,
                "pending": len(self._pending),
                "cycle_ms_fast": (round(self._dur.fast, 3)
                                  if self._dur.fast is not None else None),
                "cycle_ms_slow": (round(self._dur.slow, 3)
                                  if self._dur.slow is not None else None),
                "rss_mb_fast": (round(self._rss.fast, 2)
                                if self._rss.fast is not None else None),
                "rss_mb_slow": (round(self._rss.slow, 2)
                                if self._rss.slow is not None else None),
                "drift_total": metrics.timeline_drift_total(),
            }

    def recent(self, n: int = 32) -> List[dict]:
        with self._lock:
            return list(self._ring)[-n:]


TIMELINE = Timeline()


def _on_cycle(root) -> None:
    TIMELINE.tick(root)


def arm(directory: Optional[str] = None, capacity: int = 2048,
        spill_every: int = 256) -> Timeline:
    """Arm the module timeline and hook cycle ends (idempotent)."""
    from . import spans as _spans
    TIMELINE.arm(directory, capacity, spill_every)
    if _on_cycle not in _spans.CYCLE_HOOKS:
        _spans.CYCLE_HOOKS.append(_on_cycle)
    return TIMELINE


def disarm() -> None:
    from . import spans as _spans
    while _on_cycle in _spans.CYCLE_HOOKS:
        _spans.CYCLE_HOOKS.remove(_on_cycle)
    TIMELINE.disarm()


def armed() -> bool:
    return TIMELINE._armed


def flush() -> None:
    TIMELINE.flush()


def stats() -> dict:
    return TIMELINE.stats()


def recent(n: int = 32) -> List[dict]:
    return TIMELINE.recent(n)
