"""Chrome trace-event export — span trees as Perfetto-loadable JSON.

The format is the Trace Event "JSON Object Format": a dict with a
``traceEvents`` list of complete events (``"ph": "X"``, timestamps and
durations in microseconds). chrome://tracing and ui.perfetto.dev both
load it directly, which is the whole point: a scheduling cycle's host
phases, kernel dispatches, blocking readbacks, XLA compile events and
(grafted) sidecar solve spans land on one zoomable timeline next to the
``jax.profiler`` device capture written into the same directory by
``--profile-cycles``.

Lanes: pid "kubebatch" carries local spans; subtrees marked
``remote=True`` (the grafted sidecar roots) get pid "sidecar" so the rpc
hop reads as a cross-process flow rather than a mislabeled local call.

Arming: ``arm(dir)`` registers a cycle hook that buffers each finished
cycle root (bounded ring — a soak must not grow memory) and ``flush()``
(atexit-registered, also called by the CLI/bench at end) writes
``<dir>/trace.json``.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from collections import deque
from typing import List, Optional

from .spans import CYCLE_HOOKS, Span

__all__ = ["to_trace_events", "to_chrome_trace", "write_trace", "arm",
           "flush", "armed_dir", "disarm"]

#: bounded cycle buffer for the armed exporter — big enough for any
#: dryrun/bench window, bounded for a multi-hour soak
_MAX_BUFFERED_CYCLES = 512

_lock = threading.Lock()
_buffer: deque = deque(maxlen=_MAX_BUFFERED_CYCLES)
_dir: Optional[str] = None
_atexit_installed = False


def _emit(events: List[dict], sp: Span, pid: str, tid: int) -> None:
    if sp.args and sp.args.get("remote"):
        pid = "sidecar"
    ev = {"name": sp.name, "cat": sp.cat, "ph": "X",
          "ts": round(sp.t0 * 1e6, 3), "dur": round(sp.dur * 1e6, 3),
          "pid": pid, "tid": tid}
    if sp.args:
        ev["args"] = {k: v for k, v in sp.args.items() if k != "remote"}
    events.append(ev)
    for child in sp.children:
        _emit(events, child, pid, tid)


def to_trace_events(roots) -> List[dict]:
    """Flatten span trees into a trace-event list."""
    events: List[dict] = []
    for root in roots:
        _emit(events, root, "kubebatch", 1)
    return events


def to_chrome_trace(roots) -> dict:
    """The JSON Object Format document for a set of cycle roots."""
    return {"traceEvents": to_trace_events(roots),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "kubebatch_tpu.obs"}}


def write_trace(path: str, roots) -> str:
    """Write the trace document; returns the path."""
    doc = to_chrome_trace(roots)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)          # a killed writer never leaves half a file
    return path


# ---------------------------------------------------------------------
# armed per-cycle export
# ---------------------------------------------------------------------

def _on_cycle(root: Span) -> None:
    with _lock:
        if _dir is not None:
            _buffer.append(root)


def arm(directory: str) -> str:
    """Buffer every finished cycle and write ``<directory>/trace.json``
    at flush/exit. Returns the trace file path."""
    global _dir, _atexit_installed
    os.makedirs(directory, exist_ok=True)
    with _lock:
        _dir = directory
        if _on_cycle not in CYCLE_HOOKS:
            CYCLE_HOOKS.append(_on_cycle)
        if not _atexit_installed:
            atexit.register(flush)
            _atexit_installed = True
    return os.path.join(directory, "trace.json")


def armed_dir() -> Optional[str]:
    return _dir


def flush() -> Optional[str]:
    """Write the buffered cycles (if armed and non-empty); returns the
    written path or None. Best-effort at interpreter exit."""
    with _lock:
        directory = _dir
        roots = list(_buffer)
    if directory is None or not roots:
        return None
    try:
        return write_trace(os.path.join(directory, "trace.json"), roots)
    except Exception:                      # pragma: no cover — exit path
        return None


def disarm() -> None:
    """Tests: stop buffering and drop state."""
    global _dir
    with _lock:
        _dir = None
        _buffer.clear()
    try:
        CYCLE_HOOKS.remove(_on_cycle)
    except ValueError:
        pass
