"""Unschedulability explainer — WHY is a pending pod still pending?

kube-batch answers this with per-pod ``Unschedulable`` events written
back to the API server; the TPU-native equivalent has to answer it from
the device-resident predicate state instead. This is an OPT-IN debug
pass (never on the steady hot path): it evaluates, for every still-
pending task, which of a fixed reason set fails on each candidate node,
folds the per-(task, node) failure bitmask into per-task reason counts
on device, and reads the counts back in EXACTLY ONE blocking transfer.
The counts then fold into structured per-job reasons on the host:

    {"job": "sim/job-0042", "pending": 143, "unschedulable": 143,
     "reasons": {"port-conflict": 143}, ...}

meaning "143 tasks failed port-conflict on all candidate nodes".

Reason semantics (evaluated over CANDIDATE nodes — real, schedulable
rows; identical in the device kernel and the host oracle, which the
tests pin against each other):

- ``no-candidate-nodes``  — the cluster has zero schedulable nodes;
- ``predicate``     — the task's static predicate signature row
  (node selector / required affinity / taints — kernels/encode.py)
  excludes the node;
- ``resources``     — some resreq dimension exceeds the node's idle
  capacity (the task cannot allocate now; it may still pipeline);
- ``task-slots``    — the node is at its max_task_num pod cap;
- ``port-conflict`` — a required host port is already claimed on the
  node (affinity vocabulary present only).

A reason is BLOCKING for a task when it fails on every candidate node;
a task is unschedulable when no candidate node passes all reasons.
Both derivations run on the same [T, R] count matrix, so the device
pass and the numpy host oracle agree exactly or the test fails.
"""
from __future__ import annotations

import threading
import time
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

REASONS = ("predicate", "resources", "task-slots", "port-conflict")

__all__ = ["REASONS", "failure_counts_host", "failure_counts_device",
           "fold_reasons", "explain_session", "latest", "set_latest"]


# ---------------------------------------------------------------------
# device pass — one jitted reduction, ONE blocking readback
# ---------------------------------------------------------------------

@partial(jax.jit, static_argnames=("has_ports",))
def _explain_kernel(idle, node_ok, n_tasks, max_task_num, sig_pred,
                    task_sig, task_valid, resreq, task_ports, port_base,
                    has_ports):
    """Per-task failure counts over candidate nodes, packed as one
    [T, 6] int32 block: 4 reason columns + eligible-node count +
    broadcast candidate count (ONE readback for everything)."""
    import jax.numpy as jnp

    cand = node_ok                                     # [N] bool
    n_cand = jnp.sum(cand.astype(jnp.int32))
    pred_ok = sig_pred[task_sig]                       # [T, N] bool
    res_ok = jnp.all(resreq[:, None, :] <= idle[None, :, :],
                     axis=-1)                          # [T, N]
    slots_ok = jnp.broadcast_to((n_tasks < max_task_num)[None, :],
                                res_ok.shape)          # [T, N]
    if has_ports:
        # conflict iff any required port is already claimed on the node
        conflict = jnp.einsum("tp,np->tn", task_ports.astype(jnp.int32),
                              port_base.astype(jnp.int32)) > 0
        ports_ok = ~conflict
    else:
        ports_ok = jnp.ones_like(pred_ok)
    candf = cand[None, :]

    def count_fail(ok):
        return jnp.sum((~ok & candf).astype(jnp.int32), axis=1)

    counts = jnp.stack([count_fail(pred_ok), count_fail(res_ok),
                        count_fail(slots_ok), count_fail(ports_ok)],
                       axis=1)                         # [T, 4]
    eligible = jnp.sum((pred_ok & res_ok & slots_ok & ports_ok
                        & candf).astype(jnp.int32), axis=1)   # [T]
    tvalid = task_valid.astype(jnp.int32)
    packed = jnp.concatenate(
        [counts * tvalid[:, None], (eligible * tvalid)[:, None],
         jnp.full_like(tvalid, n_cand)[:, None]], axis=1)
    return packed


def failure_counts_device(inputs) -> Tuple[np.ndarray, np.ndarray, int]:
    """(counts [T_real, 4], eligible [T_real], n_candidates) from the
    DEVICE arrays — the one-extra-readback debug pass. Reads the device
    session's live capacity carry, so it describes the state the NEXT
    solve would see."""
    import jax.numpy as jnp

    from ..metrics import count_blocking_readback

    device = inputs.device
    aff = inputs.affinity
    has_ports = bool(aff is not None and np.any(aff.task_ports))
    if has_ports:
        task_ports = jnp.asarray(aff.task_ports)
        port_base = jnp.asarray(aff.port_base)
    else:
        # zero-width placeholders keep the signature shape-stable
        t_pad = inputs.task_valid.shape[0]
        task_ports = jnp.zeros((t_pad, 1), bool)
        port_base = jnp.zeros((device.n_padded, 1), bool)
    packed = _explain_kernel(
        device.idle, device.node_ok, device.n_tasks, device.max_task_num,
        jnp.asarray(inputs.sig_pred), jnp.asarray(inputs.task_sig),
        jnp.asarray(inputs.task_valid), jnp.asarray(inputs.resreq),
        task_ports, port_base, has_ports=has_ports)
    count_blocking_readback()
    host = np.asarray(packed)          # the explainer's ONE blocking read
    n_real = len(inputs.tasks)
    return (host[:n_real, :4], host[:n_real, 4],
            int(host[0, 5]) if len(host) else 0)


# ---------------------------------------------------------------------
# host oracle — same semantics from the numpy mirrors, zero device work
# ---------------------------------------------------------------------

def failure_counts_host(inputs) -> Tuple[np.ndarray, np.ndarray, int]:
    """The numpy twin of failure_counts_device, computed from the
    DeviceSession's host mirror (NodeState) — the oracle the device pass
    is pinned against, and the fallback when no device session exists."""
    state = inputs.device.state
    cand = np.asarray(state.schedulable & state.valid)          # [N_pad]
    n_cand = int(cand.sum())
    t_real = len(inputs.tasks)
    idle = np.asarray(state.idle, np.float32)
    pred_ok = np.asarray(inputs.sig_pred)[
        np.asarray(inputs.task_sig)[:t_real]]                   # [T, N]
    res_ok = np.all(np.asarray(inputs.resreq, np.float32)[:t_real, None, :]
                    <= idle[None, :, :], axis=-1)
    slots_ok = np.broadcast_to(
        (np.asarray(state.n_tasks)
         < np.asarray(state.max_task_num))[None, :], res_ok.shape)
    aff = inputs.affinity
    if aff is not None and np.any(aff.task_ports):
        conflict = (aff.task_ports[:t_real].astype(np.int32)
                    @ aff.port_base.T.astype(np.int32)) > 0
        ports_ok = ~conflict
    else:
        ports_ok = np.ones_like(pred_ok)
    candf = cand[None, :]

    def count_fail(ok):
        return np.sum(~ok & candf, axis=1).astype(np.int32)

    counts = np.stack([count_fail(pred_ok), count_fail(res_ok),
                       count_fail(slots_ok), count_fail(ports_ok)], axis=1)
    eligible = np.sum(pred_ok & res_ok & slots_ok & ports_ok & candf,
                      axis=1).astype(np.int32)
    return counts, eligible, n_cand


# ---------------------------------------------------------------------
# folding into per-job structured reasons
# ---------------------------------------------------------------------

def fold_reasons(inputs, counts: np.ndarray, eligible: np.ndarray,
                 n_cand: int) -> dict:
    """Fold the [T, R] failure-count matrix into the structured snapshot
    served by /debug/explain and printed by sim summaries."""
    per_job: Dict[int, dict] = {}
    task_job = np.asarray(inputs.task_job)
    for i in range(len(inputs.tasks)):
        ji = int(task_job[i])
        rec = per_job.get(ji)
        if rec is None:
            job = inputs.jobs[ji] if 0 <= ji < len(inputs.jobs) else None
            rec = per_job[ji] = {
                "job": (f"{job.namespace}/{job.name}" if job is not None
                        else f"job[{ji}]"),
                "pending": 0, "unschedulable": 0,
                "reasons": {},
            }
        rec["pending"] += 1
        if n_cand == 0:
            rec["unschedulable"] += 1
            rec["reasons"]["no-candidate-nodes"] = \
                rec["reasons"].get("no-candidate-nodes", 0) + 1
            continue
        if int(eligible[i]) == 0:
            rec["unschedulable"] += 1
            for r, name in enumerate(REASONS):
                if int(counts[i, r]) == n_cand:
                    rec["reasons"][name] = rec["reasons"].get(name, 0) + 1
    jobs = sorted(per_job.values(),
                  key=lambda r: (-r["unschedulable"], r["job"]))
    return {
        "ts": time.time(),
        "candidate_nodes": n_cand,
        "pending_tasks": int(sum(r["pending"] for r in jobs)),
        "unschedulable_tasks": int(sum(r["unschedulable"] for r in jobs)),
        "jobs": [r for r in jobs if r["pending"]],
    }


def summarize(snapshot: dict, limit: int = 8) -> List[str]:
    """Human lines — the kube-batch per-pod-event analogue, per JOB:
    '143 tasks failed port-conflict on all candidate nodes'."""
    lines = []
    for rec in snapshot.get("jobs", ())[:limit]:
        if not rec["unschedulable"]:
            continue
        if rec["reasons"]:
            why = "; ".join(
                f"{n} tasks failed {reason} on all candidate nodes"
                for reason, n in sorted(rec["reasons"].items(),
                                        key=lambda kv: -kv[1]))
        else:
            why = (f"{rec['unschedulable']} tasks have no single node "
                   f"passing every reason (mixed per-node failures)")
        lines.append(f"{rec['job']}: {why}")
    return lines


# ---------------------------------------------------------------------
# session entry point + the /debug/explain snapshot
# ---------------------------------------------------------------------

_lock = threading.Lock()
_latest: Optional[dict] = None


def explain_session(ssn, device_pass: bool = True) -> dict:
    """Run the explainer against a live Session (post-actions, pre-close:
    the pending set is what this cycle could not place). Builds cycle
    inputs through the SAME tensorize path the solvers use (the cached
    incremental device snapshot is reused, not rebuilt), runs the device
    reduction (one readback) or the host oracle, folds, and publishes
    the snapshot for /debug/explain."""
    from ..actions.cycle_inputs import EMPTY_CYCLE, build_cycle_inputs

    inputs = build_cycle_inputs(ssn, allow_affinity=True)
    if inputs is EMPTY_CYCLE:
        snap = {"ts": time.time(), "candidate_nodes": len(ssn.nodes),
                "pending_tasks": 0, "unschedulable_tasks": 0, "jobs": []}
    elif inputs is None:
        # over-vocabulary / host-path cycle: no device arrays to fold —
        # report that honestly instead of half an answer
        snap = {"ts": time.time(), "error":
                "cycle features exceed the device vocabulary; "
                "explainer has no predicate tensors for this snapshot"}
    else:
        if device_pass:
            counts, eligible, n_cand = failure_counts_device(inputs)
        else:
            counts, eligible, n_cand = failure_counts_host(inputs)
        snap = fold_reasons(inputs, counts, eligible, n_cand)
    set_latest(snap)
    return snap


def set_latest(snapshot: Optional[dict]) -> None:
    global _latest
    with _lock:
        _latest = snapshot


def latest() -> Optional[dict]:
    """The most recent snapshot (None when the explainer never ran —
    it is off by default and costs nothing until invoked)."""
    with _lock:
        return _latest
