"""Cluster API objects — the vocabulary the scheduler watches and mutates.

These are the framework's equivalent of the Kubernetes core/v1 + CRD types the
reference consumes (ref: pkg/apis/scheduling/v1alpha1/types.go, plus the
subset of v1.Pod / v1.Node fields the scheduler actually reads). They are
plain dataclasses so that synthetic event streams, tests and the gRPC
boundary can construct them cheaply; nothing in here imports JAX.

Resource quantities convention (ref: pkg/scheduler/api/resource_info.go:58-73):
CPU and GPU are *milli* units, memory is bytes, ``pods`` is a count.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Tuple

# --- well-known keys (ref: pkg/apis/scheduling/v1alpha1/labels.go:221-223) ---
GROUP_NAME_ANNOTATION = "scheduling.k8s.io/group-name"
BACKFILL_ANNOTATION = "scheduling.k8s.io/kube-batch/backfill"

# resource names (ref: resource_info.go:37, v1.ResourceCPU/Memory/Pods)
CPU = "cpu"
MEMORY = "memory"
GPU = "nvidia.com/gpu"
PODS = "pods"

DEFAULT_SCHEDULER_NAME = "kube-batch"

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_uid_counter):08d}"


_QUANTITY_SUFFIXES = {
    "Ki": 1024.0, "Mi": 1024.0 ** 2, "Gi": 1024.0 ** 3, "Ti": 1024.0 ** 4,
    "Pi": 1024.0 ** 5, "Ei": 1024.0 ** 6,
    "n": 1e-9, "u": 1e-6, "m": 1e-3,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
}


def parse_quantity(s) -> float:
    """Parse a Kubernetes resource.Quantity string to its plain value
    ("500m" -> 0.5, "1Gi" -> 1073741824, "2" -> 2.0, "1e3" -> 1000.0) —
    the subset of the apimachinery Quantity grammar pod specs actually use
    (binary Ki..Ei, decimal n/u/m/k..E, plain and scientific numbers)."""
    if isinstance(s, (int, float)):
        return float(s)
    s = s.strip()
    for suffix, mult in _QUANTITY_SUFFIXES.items():
        if s.endswith(suffix):
            head = s[:-len(suffix)]
            # "1e3" must parse as scientific, not exa ("E" suffix needs a
            # bare integer head; "1e3E" is not produced by k8s anyway)
            if suffix == "E" and ("e" in head or "E" in head):
                continue
            return float(head) * mult
    return float(s)


def resource_list(cpu=0.0, memory=0.0, gpu=0.0, pods=0.0) -> Dict[str, float]:
    """Build a ResourceList. Numeric arguments follow the internal
    convention (cpu/gpu in MILLIS, memory in bytes); string arguments are
    Kubernetes quantity strings with their k8s meaning (cpu="1" is one
    core = 1000 millis, cpu="500m" is 500 millis, memory="1Gi" is
    1073741824 bytes), matching what a pod spec would carry."""
    def _cores_to_millis(v):
        return parse_quantity(v) * 1000.0 if isinstance(v, str) else float(v)

    rl: Dict[str, float] = {}
    for key, value in ((CPU, _cores_to_millis(cpu)),
                       (MEMORY, parse_quantity(memory)),
                       (GPU, _cores_to_millis(gpu)),
                       (PODS, parse_quantity(pods))):
        if value:       # "0"/"0m" and 0 alike omit the key
            rl[key] = value
    return rl


class PodPhase(str, Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


class TaintEffect(str, Enum):
    NO_SCHEDULE = "NoSchedule"
    PREFER_NO_SCHEDULE = "PreferNoSchedule"
    NO_EXECUTE = "NoExecute"


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: TaintEffect = TaintEffect.NO_SCHEDULE


@dataclass
class Toleration:
    key: str = ""            # empty key + Exists matches everything
    operator: str = "Equal"  # "Equal" | "Exists"
    value: str = ""
    effect: str = ""         # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect.value:
            return False
        if not self.key and self.operator == "Exists":
            return True
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class MatchExpression:
    """A single node/pod selector requirement (key op values)."""
    key: str
    operator: str            # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        has = self.key in labels
        val = labels.get(self.key)
        if self.operator == "In":
            return has and val in self.values
        if self.operator == "NotIn":
            return not has or val not in self.values
        if self.operator == "Exists":
            return has
        if self.operator == "DoesNotExist":
            return not has
        if self.operator in ("Gt", "Lt"):
            lhs = _as_int(val) if has else None
            rhs = _as_int(self.values[0]) if self.values else None
            if lhs is None or rhs is None:
                return False
            return lhs > rhs if self.operator == "Gt" else lhs < rhs
        return False


def _as_int(v) -> Optional[int]:
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


@dataclass
class NodeSelectorTerm:
    match_expressions: List[MatchExpression] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(e.matches(labels) for e in self.match_expressions)


@dataclass
class NodeAffinity:
    # ORed terms; empty list = no requirement
    required: List[NodeSelectorTerm] = field(default_factory=list)
    # (weight, term) preferences summed into node score
    preferred: List[Tuple[int, NodeSelectorTerm]] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    """Inter-pod (anti-)affinity term: match pods by label selector within a
    topology domain (we support the node-hostname topology, the only one the
    reference's e2e suite exercises)."""
    match_labels: Dict[str, str] = field(default_factory=dict)
    topology_key: str = "kubernetes.io/hostname"
    namespaces: List[str] = field(default_factory=list)  # empty = pod's own ns

    def selects(self, pod: "Pod") -> bool:
        return all(pod.labels.get(k) == v for k, v in self.match_labels.items())


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity_required: List[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_required: List[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: List[Tuple[int, PodAffinityTerm]] = field(default_factory=list)
    pod_anti_affinity_preferred: List[Tuple[int, PodAffinityTerm]] = field(default_factory=list)


@dataclass
class Container:
    requests: Dict[str, float] = field(default_factory=dict)
    ports: List[int] = field(default_factory=list)  # host ports


@dataclass
class Pod:
    """The subset of v1.Pod the scheduler reads."""
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=lambda: new_uid("pod"))
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    node_name: str = ""
    phase: PodPhase = PodPhase.PENDING
    priority: Optional[int] = None
    priority_class_name: str = ""
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    deletion_timestamp: Optional[float] = None
    creation_timestamp: float = 0.0
    owner_uid: str = ""       # controller owner (ref: pkg/apis/utils/utils.go:305)
    status_conditions: List[Dict[str, str]] = field(default_factory=list)
    #: PersistentVolumeClaim names this pod mounts (same namespace);
    #: consumed by the PV-aware volume binder seam (sim/source.py)
    pvc_names: List[str] = field(default_factory=list)

    @property
    def group_name(self) -> str:
        return self.annotations.get(GROUP_NAME_ANNOTATION, "")

    def host_ports(self) -> List[int]:
        ports: List[int] = []
        for c in self.containers:
            ports.extend(c.ports)
        return ports

    def has_host_ports(self) -> bool:
        """Memoized truthiness of host_ports() — the device-feature screen
        asks this per pending pod per cycle, and container specs are
        immutable for the pod's lifetime."""
        flag = getattr(self, "_kb_hostports", None)
        if flag is None:
            flag = any(c.ports for c in self.containers)
            self._kb_hostports = flag
        return flag

    def has_pod_affinity(self) -> bool:
        """Any inter-pod (anti-)affinity term — the feature class that
        makes predicates/scores allocation-dependent (kernels/encode.py
        dynamic_features). Memoized: pod spec fields are immutable for
        the pod's lifetime."""
        flag = getattr(self, "_kb_podaff", None)
        if flag is None:
            aff = self.affinity
            flag = bool(aff is not None
                        and (aff.pod_affinity_required
                             or aff.pod_anti_affinity_required
                             or aff.pod_affinity_preferred
                             or aff.pod_anti_affinity_preferred))
            self._kb_podaff = flag
        return flag


class PodGroupPhase(str, Enum):
    """ref: pkg/apis/scheduling/v1alpha1/types.go:28-39"""
    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"


# PodGroup condition types (ref: types.go:41-46; Backfilled is fork-specific)
UNSCHEDULABLE_CONDITION = "Unschedulable"
BACKFILLED_CONDITION = "Backfilled"
NOT_ENOUGH_RESOURCES_REASON = "NotEnoughResources"
NOT_ENOUGH_PODS_REASON = "NotEnoughPodsScheduled"


@dataclass
class PodGroupCondition:
    type: str
    status: str = "True"
    transition_id: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class PodGroupStatus:
    phase: PodGroupPhase = PodGroupPhase.PENDING
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class PodGroup:
    """ref: pkg/apis/scheduling/v1alpha1/types.go:90-149"""
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=lambda: new_uid("pg"))
    min_member: int = 0
    #: desired membership for elastic gangs (0 = fixed-size: desired ==
    #: min_member). A gang with allocated >= min_member but < max_member
    #: is AlmostReady — schedulable at its minimum, backfilled later.
    max_member: int = 0
    queue: str = ""
    priority_class_name: str = ""
    creation_timestamp: float = 0.0
    annotations: Dict[str, str] = field(default_factory=dict)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)


@dataclass
class Queue:
    """ref: pkg/apis/scheduling/v1alpha1/types.go:170-186"""
    name: str
    weight: int = 1
    uid: str = field(default_factory=lambda: new_uid("queue"))


@dataclass
class PriorityClass:
    name: str
    value: int = 0
    global_default: bool = False


@dataclass
class PodDisruptionBudget:
    """Legacy gang-grouping path kept for reference parity
    (ref: job_info.go:204-211; cache/event_handlers.go:477-515)."""
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=lambda: new_uid("pdb"))
    min_available: int = 0
    match_labels: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    owner_uid: str = ""


@dataclass
class Node:
    """The subset of v1.Node the scheduler reads."""
    name: str
    uid: str = field(default_factory=lambda: new_uid("node"))
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    allocatable: Dict[str, float] = field(default_factory=dict)
    capacity: Dict[str, float] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False

    def __post_init__(self):
        if not self.capacity and self.allocatable:
            self.capacity = dict(self.allocatable)
        # every node implicitly carries its hostname label, like kubelet does
        self.labels.setdefault("kubernetes.io/hostname", self.name)


def is_backfill_pod(pod: Pod) -> bool:
    """ref: pkg/scheduler/api/job_info.go:72-84 (invalid values -> False)."""
    val = pod.annotations.get(BACKFILL_ANNOTATION, "")
    if not val:
        return False
    return val.strip().lower() in ("1", "t", "true")
