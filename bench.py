#!/usr/bin/env python
"""Benchmark driver — measures scheduling-cycle latency on the BASELINE.md
configs and prints the result as a JSON line.

Output contract: the LAST stdout line is the result. A cpu-fallback cfg5
run may print TWO JSON lines (kill-safe primary first, enriched last) —
consumers must take the last line. Process-level runs also append every
emitted line (with timestamp + git SHA) to BENCH_DEVICE.jsonl, the
committed evidence record; programmatic main(argv) calls (tests) do not.

The reference publishes no numbers (BASELINE.md: "measured, not copied");
`vs_baseline` is therefore reported against the north-star target of 15 ms
p50 cycle latency at the stress config — vs_baseline > 1.0 means beating
the target.

Usage: python bench.py [--config N] [--cycles M]
                       [--mode batched|fused|jax|host]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


#: argv to stamp evidence lines with; None = recording disabled (the
#: default for programmatic main(argv) calls, so tests with stubbed
#: timings can never pollute the committed evidence file)
RECORD_ARGV = None


def record_line(out: dict, partial: bool = False) -> None:
    """Append the emitted JSON line to the committed, append-only
    BENCH_DEVICE.jsonl evidence file — stamped with wall-clock time and
    git SHA at measurement time, whatever the backend. This is the
    artifact of record for device numbers: prose transcription of
    transient tunnel windows is not (round-4 verdict, weakness 2).
    Best-effort: a broken stamp must never cost the stdout line."""
    if RECORD_ARGV is None:
        return
    try:
        import subprocess
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"], cwd=here,
                capture_output=True, text=True, timeout=10,
            ).stdout.strip() or "unknown"
        except Exception:
            sha = "unknown"
        stamped = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                   "git_sha": sha, "argv": list(RECORD_ARGV), **out}
        if partial:
            # kill-safe primary row of a run whose enriched row follows;
            # evidence-file aggregators must not double-count the run
            stamped["partial"] = True
        with open(os.path.join(here, "BENCH_DEVICE.jsonl"), "a") as f:
            f.write(json.dumps(stamped) + "\n")
    except Exception:
        pass


def memory_fields() -> dict:
    """Peak-memory evidence for every bench line (ISSUE 10 satellite —
    the narrowed-intermediate claim must be a measured number, not
    prose): ``memory_peak_mb`` is the accelerator's own
    ``peak_bytes_in_use`` where the backend exposes memory_stats()
    (memory_peak_src="device"); on backends that don't (host XLA), the
    process peak RSS stands in, labeled honestly
    (memory_peak_src="rss"). ``host_rss_peak_mb`` (ru_maxrss, MiB) is
    always reported alongside."""
    out = {}
    try:
        import resource as _resource
        out["host_rss_peak_mb"] = round(
            _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss / 1024.0,
            1)
    except Exception:
        pass
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if peak:
            out["memory_peak_mb"] = round(peak / 2.0 ** 20, 1)
            out["memory_peak_src"] = "device"
    except Exception:
        pass
    if "memory_peak_mb" not in out and "host_rss_peak_mb" in out:
        out["memory_peak_mb"] = out["host_rss_peak_mb"]
        out["memory_peak_src"] = "rss"
    return out


def emit(out: dict, flush: bool = False, partial: bool = False) -> None:
    """Print a bench JSON line AND record it to BENCH_DEVICE.jsonl.
    Every line carries the peak-memory fields (memory_fields),
    REFRESHED at emit time — the cfg5 cpu-fallback path emits the same
    dict twice (kill-safe partial, then enriched final after the steady
    extra), and the final line must carry the true process peak, not
    the partial emit's stale snapshot."""
    out.update(memory_fields())
    narrow_env = os.environ.get("KUBEBATCH_NARROW", "")
    if narrow_env:
        # label forced-dtype A/B arms (argv alone can't tell them apart)
        out.setdefault("narrow_env", narrow_env)
    print(json.dumps(out), flush=flush)
    record_line(out, partial=partial)


def ensure_responsive_backend(timeout: float = 120.0) -> str:
    """The TPU tunnel can wedge so hard that backend init blocks forever
    (a bare device query hangs). The shared watchdog probes in an
    abandonable subprocess and flips THIS process to the CPU backend on
    failure (jax is preloaded but uninitialized, so the platform can
    still be switched). A slow recorded number beats a hung driver."""
    from kubebatch_tpu.runtime.watchdog import \
        ensure_responsive_backend as probe

    backend = probe(timeout, skip_env=None)   # the bench always probes
    if backend == "pinned":
        # backend already initialized on the wedged platform — running
        # would hang forever; fail loudly with a parseable line
        emit({"metric": "sched_cycle_p50_ms",
              "value": -1.0, "unit": "ms",
              "vs_baseline": 0.0,
              "error": "accelerator backend unresponsive "
                       "and platform pinned"})
        sys.exit(1)
    return backend


def _sidecar_health(address: str) -> dict:
    """Probe the sidecar's obs /healthz surface (obs/http.py). The obs
    address comes from KUBEBATCH_OBS_ADDR when set, else the default
    obs port next to the gRPC host. Returns the health JSON, or {} when
    no obs surface answers (a sidecar run without --obs — not an error,
    just unverifiable)."""
    import json as _json
    import urllib.request

    obs_addr = os.environ.get("KUBEBATCH_OBS_ADDR", "")
    if not obs_addr:
        host = address.rsplit(":", 1)[0]
        obs_addr = f"{host}:8080"
    url = f"http://{obs_addr}/healthz"
    try:
        with urllib.request.urlopen(url, timeout=2.0) as resp:
            return _json.loads(resp.read().decode())
    except Exception:
        return {}


def ensure_rpc_sidecar():
    """--mode rpc support: PROBE BEFORE SPAWN. KUBEBATCH_SOLVER_ADDR
    (when set) and the default serve() address are probed for a live
    sidecar and reused — a bench run next to a running daemon must not
    fork a second solver process (it would double device contention and
    could clash on the lease/metrics ports). A candidate that answers
    the port is then HEALTH-CHECKED through /healthz: a sidecar
    reporting "failing" (ladder at its floor) or running a different
    kubebatch version would silently poison the recorded numbers, so
    it is refused and an in-process server spawns instead. Only when
    nothing answers does an in-process server start on a free port — a
    real gRPC hop over localhost TCP, the co-located deployment shape,
    so the recorded per-dispatch cost is serialization + wire +
    queueing, not a stub. Returns (address, server_or_None); the
    caller stops the server after the run."""
    import grpc

    from kubebatch_tpu import __version__

    addr = os.environ.get("KUBEBATCH_SOLVER_ADDR", "")
    # the default serve() port is probed too: an operator's already-
    # running sidecar is reused even without the env var set
    candidates = [addr] if addr else ["127.0.0.1:50061"]
    for cand in candidates:
        try:
            ch = grpc.insecure_channel(cand)
            grpc.channel_ready_future(ch).result(timeout=2.0)
            ch.close()
        except Exception:
            if cand == addr:
                print(f"rpc sidecar {cand} unreachable; "
                      "starting in-process", file=sys.stderr)
            continue
        health = _sidecar_health(cand)
        if health:
            if health.get("status") == "failing":
                print(f"rpc sidecar {cand} reports failing "
                      f"(degradation level "
                      f"{health.get('degradation_level')}); refusing to "
                      f"bench against it — starting in-process",
                      file=sys.stderr)
                break
            peer_ver = health.get("version", "")
            if peer_ver and peer_ver != __version__:
                print(f"rpc sidecar {cand} runs kubebatch {peer_ver}, "
                      f"this bench is {__version__}; refusing the "
                      f"mismatch — starting in-process", file=sys.stderr)
                break
        else:
            print(f"rpc sidecar {cand} has no obs surface to verify "
                  f"health/version; reusing it unverified",
                  file=sys.stderr)
        os.environ["KUBEBATCH_SOLVER_ADDR"] = cand
        if cand != addr:
            print(f"reusing running rpc sidecar at {cand}",
                  file=sys.stderr)
        return cand, None
    from kubebatch_tpu.rpc.server import make_server

    server, port = make_server("127.0.0.1:0")
    server.start()
    addr = f"127.0.0.1:{port}"
    os.environ["KUBEBATCH_SOLVER_ADDR"] = addr
    return addr, server


def rpc_stats_fields(cycle_engines, rpc_addr: str) -> dict:
    """The rpc deployment-mode evidence fields, shared by the cold and
    steady bench paths (one implementation — the two modes must never
    drift apart on how the hop cost or the fallback count is derived):
    per-dispatch hop cost = client-observed RTT minus the server's own
    solve wall (serialization + wire + queueing), and rpc_fallbacks =
    the number of MEASURED CYCLES whose allocate ran a non-rpc engine
    (per-cycle engagements, not distinct engine names)."""
    from kubebatch_tpu.rpc import client as rpc_client

    stats = list(rpc_client.DISPATCH_STATS)
    hops = [max(0.0, rtt * 1e3 - solve) for rtt, solve in stats]
    out = {"rpc_sidecar": rpc_addr, "rpc_dispatches": len(stats)}
    if hops:
        out["rpc_hop_ms_p50"] = round(float(np.percentile(hops, 50)), 3)
        out["rpc_hop_ms_max"] = round(float(np.max(hops)), 3)
        out["rpc_solve_ms_p50"] = round(float(np.percentile(
            [s for _, s in stats], 50)), 3)
    out["rpc_fallbacks"] = sum(1 for e in cycle_engines if e != "rpc")
    return out


#: per-config action order — shared with compilesvc/profile.py (the
#: registered compile surface describes the same cycles the bench
#: drives); predicate-rich "2p"/"3p"/"5p" variants included
#: (labels/taints/selectors/affinity/ports at workload-ish fractions —
#: sim/cluster.py BASELINE_SPECS)
from kubebatch_tpu.conf import CONFIG_ACTIONS  # noqa: E402


def downsampled_oracle_check(config, factor: int = 50) -> dict:
    """The cfg6/cfg7 done-bar's decision check, at a scale the host
    oracle can run: the SAME spec shape downsampled by ``factor``,
    solved three ways —

    - **two-level (hier) vs the host oracle**: per-task status equality
      and bound-set equality (the repo's established oracle contract for
      the batched engine family — policy-equal; the task->node map is
      round/wave-granular by design, see kernels/batched.py);
    - **two-level vs the flat batched engine**: BIT-identical decision
      arrays (states and node choices) — the decomposition itself must
      not move a single placement at a scale where the flat engine runs.

    Returns the evidence fields for the bench line."""
    import dataclasses

    from kubebatch_tpu import actions, plugins  # noqa: F401
    from kubebatch_tpu.actions.allocate import AllocateAction
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.conf import shipped_tiers
    from kubebatch_tpu.framework import CloseSession, OpenSession
    from kubebatch_tpu.sim.cluster import BASELINE_SPECS, build_cluster

    spec = BASELINE_SPECS[config]
    spec = dataclasses.replace(
        spec, n_nodes=max(64, spec.n_nodes // factor),
        n_groups=max(8, spec.n_groups // factor))
    decisions = {}
    for mode in ("hier", "batched", "host"):
        class _B:
            def bind(self, pod, hostname):
                pod.node_name = hostname

            def evict(self, pod):
                pod.deletion_timestamp = 1.0

        cache = SchedulerCache(binder=_B(), evictor=_B(),
                               async_writeback=False)
        sim = build_cluster(spec)
        sim.populate(cache)
        ssn = OpenSession(cache, shipped_tiers())
        AllocateAction(mode=mode).execute(ssn)
        decisions[mode] = {
            t.key: (str(t.status), t.node_name)
            for job in ssn.jobs.values() for t in job.tasks.values()}
        CloseSession(ssn)
    hier, flat, host = (decisions["hier"], decisions["batched"],
                        decisions["host"])
    status_eq = all(hier[k][0] == host[k][0] for k in hier)
    bound = {k for k, v in hier.items() if v[1]}
    bound_host = {k for k, v in host.items() if v[1]}
    return {
        "oracle_downsample_factor": factor,
        "oracle_nodes": spec.n_nodes,
        "oracle_tasks_compared": len(hier),
        "oracle_status_equal": status_eq,
        "oracle_bound_set_equal": bound == bound_host,
        "hier_vs_flat_bit_identical": hier == flat,
        "oracle_downsampled_ok": (status_eq and bound == bound_host
                                  and hier == flat),
    }


def build_actions(config: int, mode: str):
    from kubebatch_tpu.actions.allocate import AllocateAction
    from kubebatch_tpu.actions.backfill import BackfillAction
    from kubebatch_tpu.actions.preempt import PreemptAction
    from kubebatch_tpu.actions.reclaim import ReclaimAction

    mk = {"allocate": lambda: AllocateAction(mode=mode),
          "backfill": BackfillAction,
          "preempt": PreemptAction,
          "reclaim": ReclaimAction}
    return [(name, mk[name]()) for name in CONFIG_ACTIONS[config]]


def run_config(config: int, cycles: int, mode: str):
    from kubebatch_tpu import actions, plugins  # noqa: F401
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.conf import shipped_tiers
    from kubebatch_tpu.framework import CloseSession, OpenSession
    from kubebatch_tpu.sim import baseline_cluster

    # the shipped config's full multi-tier stack (config/kube-batch-conf.yaml
    # parity; BASELINE cfg5 calls for the full stack)
    tiers = shipped_tiers()

    import gc

    from kubebatch_tpu.actions import allocate as _alloc_mod
    from kubebatch_tpu.metrics import (blocking_readbacks, compile_ms_total,
                                       host_phase_seconds,
                                       readback_accounting,
                                       solver_kernel_seconds)

    latencies = []
    bound_total = 0
    bind_seconds = 0.0
    evicted_total = 0
    action_seconds = {name: 0.0 for name in CONFIG_ACTIONS[config]}
    measured_cycles = 0
    engines = []   # one entry per measured cycle (rpc_fallbacks counts cycles)
    readbacks = []
    kernel_s = []
    phase_s: dict = {}
    #: first-cycle split (ISSUE 6 satellite): the cold cycle's wall used
    #: to lump XLA compile into the host share — the compile manager's
    #: counters split them, so cold lines carry cold_compile_ms (jit
    #: compile path) next to cold_host_ms (tensorize/replay/close host
    #: work) instead of one conflated number
    cold_split: dict = {}
    # GC discipline mirrors runtime/scheduler.py: automatic collection off
    # during the timed cycle (a gen2 pass scans the whole 100k+ object
    # cluster graph mid-cycle otherwise), explicit collection between
    # cycles, off the latency path
    gc.disable()
    acct0 = readback_accounting()
    try:
        for cycle in range(cycles):
            sim = baseline_cluster(config)
            binds = {}
            evicted = []

            class _B:
                def bind(self, pod, hostname):
                    binds[pod.uid] = hostname
                    pod.node_name = hostname

                def evict(self, pod):
                    evicted.append(pod.uid)
                    pod.deletion_timestamp = 1.0

            seam = _B()
            cache = SchedulerCache(binder=seam, evictor=seam,
                                   async_writeback=False)
            sim.populate(cache)
            acts = build_actions(config, mode)
            gc.collect()
            rb0 = blocking_readbacks()
            ks0 = solver_kernel_seconds()
            hp0 = host_phase_seconds()
            cm0 = compile_ms_total()
            t0 = time.perf_counter()
            ssn = OpenSession(cache, tiers)
            t1 = time.perf_counter()
            act_times = []
            for name, act in acts:
                a0 = time.perf_counter()
                act.execute(ssn)
                act_times.append((name, time.perf_counter() - a0))
            t2 = time.perf_counter()
            CloseSession(ssn)
            dt = time.perf_counter() - t0
            if os.environ.get("KB_BENCH_DEBUG"):
                per = " ".join(f"{n}={s:.3f}s" for n, s in act_times)
                print(f"cycle {cycle}: open={t1 - t0:.3f}s {per} "
                      f"close={dt - (t2 - t0):.3f}s", file=sys.stderr)
            if cycle == 0:
                # the first cycle pays jit compile — split it: compile
                # path (counters) vs the host share (phase timers)
                hp_c = host_phase_seconds()
                cold_split = {
                    "cold_wall_ms": round(dt * 1e3, 3),
                    "cold_compile_ms": round(compile_ms_total() - cm0, 3),
                    "cold_host_ms": round(1e3 * sum(
                        hp_c[k] - hp0.get(k, 0.0) for k in hp_c), 3),
                }
                if config in (6, 7):
                    # scale-axis lines must pin recompiles POST-warm-up:
                    # cycle 0 traced the two-level surface; from here a
                    # compile is a counted recompile on the line
                    from kubebatch_tpu import compilesvc
                    compilesvc.mark_warm()
                if cycles > 1:
                    # measured-window accounting excludes the cold cycle
                    # (it pays compile, not representative transfers)
                    acct0 = readback_accounting()
            if cycle > 0 or cycles == 1:   # first cycle pays jit compile
                latencies.append(dt)
                bound_total += len(binds)
                bind_seconds += dt
                evicted_total += len(evicted)
                for name, s in act_times:
                    action_seconds[name] += s
                measured_cycles += 1
                engines.append(_alloc_mod.last_cycle_engine)
                readbacks.append(blocking_readbacks() - rb0)
                kernel_s.append(solver_kernel_seconds() - ks0)
                hp = host_phase_seconds()
                for k in hp:
                    phase_s.setdefault(k, []).append(hp[k] - hp0.get(k, 0.0))
    finally:
        gc.enable()
    acct = readback_accounting(since=acct0)
    action_ms = {name: round(1e3 * s / max(1, measured_cycles), 3)
                 for name, s in action_seconds.items()}
    # the cold-cycle host split (VERDICT r5 directive 1): per-phase MEDIAN
    # ms per cycle, from the committed phase counters — wall-time medians
    # because the bench box throttles in bursts
    phase_ms = {k: round(1e3 * float(np.median(v)), 3)
                for k, v in sorted(phase_s.items())}
    return (latencies, bound_total, bind_seconds, evicted_total, action_ms,
            engines, readbacks, kernel_s, phase_ms, cold_split, acct)


def run_steady(config, cycles: int, mode: str, churn_pods: int,
               skew: bool = False, trace: str = ""):
    """Steady-state regime: ONE persistent cache, fully scheduled in a
    warmup cycle, then a churn trickle per measured cycle (whole gangs
    finish, equal fresh gangs arrive). This is where the incremental
    snapshot/device-state reuse pays: the measured cycle re-clones and
    re-packs only the churned entities.

    ``skew``: every tick's fresh gangs land on ONE queue, alternating
    between the two extreme-weight queues — sustained cross-queue
    imbalance, so the reclaim gates correctly stay open and the victim
    wave path is measured hot (VERDICT r4 directive 4)."""
    import gc

    from kubebatch_tpu import actions, plugins  # noqa: F401
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.conf import shipped_tiers
    from kubebatch_tpu.framework import CloseSession, OpenSession
    from kubebatch_tpu.sim import baseline_cluster

    from kubebatch_tpu.objects import PodPhase

    tiers = shipped_tiers()
    sim = baseline_cluster(config)
    binds = {}
    fresh_binds = []

    class _B:
        def bind(self, pod, hostname):
            binds[pod.uid] = hostname
            pod.node_name = hostname
            fresh_binds.append(pod)

        def bind_many(self, pairs):
            # batched binder seam (ISSUE 9 apply path): one call per
            # decision chunk — what a production bulk-Binding POST does
            for pod, hostname in pairs:
                binds[pod.uid] = hostname
                pod.node_name = hostname
                fresh_binds.append(pod)

        def evict(self, pod):
            pod.deletion_timestamp = 1.0

    seam = _B()
    cache = SchedulerCache(binder=seam, evictor=seam, async_writeback=False)
    sim.populate(cache)
    acts = build_actions(config, mode)

    def kubelet_tick():
        """Bound pods start Running (update events), outside the timed
        window — the snapshot work these dirty the next cycle with is
        real scheduler cost and stays inside it."""
        for pod in fresh_binds:
            if pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                cache.update_pod(pod, pod)
        fresh_binds.clear()

    tick_no = [0]

    replayer = None
    if trace:
        # --trace replaces the synthetic churn templates: arrivals come
        # from the workloads/ plane (diurnal + heavy-tail + elastic),
        # applied synchronously to the cache. Calibrated so steady
        # concurrent trace pods ~= 4x the churn level at ~8-cycle gang
        # lifetimes, i.e. per-cycle event volume near the synthetic
        # regime's.
        from kubebatch_tpu.workloads import TraceReplayer
        _, records, dt = _build_trace(
            trace, target_pods=4 * max(1, churn_pods),
            cycles=cycles + 8, lifetime_cycles=8,
            cpu_milli=sim.spec.pod_cpu_millis,
            mem_bytes=sim.spec.pod_mem_bytes,
            n_queues=max(1, len(sim.queues)))
        replayer = TraceReplayer(records, _DirectEmitter(cache),
                                 [q.name for q in sim.queues], dt=dt)

    def churn():
        """Per-cycle arrivals; under --steady-skew they alternate between
        the two extreme-weight queues so cross-queue imbalance persists."""
        if replayer is not None:
            replayer.tick()
            return
        arrival = None
        if skew:
            nq = max(1, len(sim.queues))
            arrival = 0 if tick_no[0] % 2 == 0 else nq - 1
            tick_no[0] += 1
        sim.churn_tick(cache, churn_pods, arrival_queue=arrival)

    import resource as _resource

    gc.disable()
    try:
        # warmup: schedule the whole cluster (plus one cheap settle cycle
        # so the first measured cycle starts from an adopted base)
        for _ in range(2):
            ssn = OpenSession(cache, tiers)
            for _, act in acts:
                act.execute(ssn)
            CloseSession(ssn)
            kubelet_tick()
        # two unmeasured CHURN cycles: the victim kernels only trace once
        # pending work exists (the full-schedule warmup has none), and
        # the second churn cycle hits the remaining kernel shapes — so
        # the measured cycles describe scheduling, not jit compiles
        for _ in range(2):
            kubelet_tick()
            churn()
            ssn = OpenSession(cache, tiers)
            for _, act in acts:
                act.execute(ssn)
            CloseSession(ssn)
        from kubebatch_tpu import compilesvc
        from kubebatch_tpu.actions import allocate as _alloc_mod
        from kubebatch_tpu.metrics import (blocking_readbacks,
                                           host_phase_seconds,
                                           readback_accounting,
                                           recompiles_total)

        # the warm-up / churn cycles above traced every steady shape:
        # from here a real compile is a counted recompile, and the
        # steady line FAILS on a nonzero count (ISSUE 6 enforcement —
        # a compile wall mid-steady-cycle must never pass silently)
        compilesvc.mark_warm()
        recompiles0 = recompiles_total()
        # readbacks-per-decision window (metrics.readback_accounting):
        # the telemetry frames count every bound task as a decision, so
        # the measured window's transfer cost is reported per unit of
        # scheduling work, not just per cycle
        acct0 = readback_accounting()
        latencies = []
        bound = 0
        action_seconds = {name: 0.0 for name in CONFIG_ACTIONS[config]}
        readbacks = []
        engines = []   # one entry per measured cycle
        # span-tree evidence (ISSUE 7): each measured cycle runs under an
        # obs cycle root, so the line can report spans_per_cycle and the
        # calibrated cost of always-on tracing next to the wall numbers
        from kubebatch_tpu import obs
        span_counts = []
        trace_roots = []
        phase_s: dict = {}
        for cycle in range(cycles):
            before = len(binds)
            kubelet_tick()
            churn()
            gc.collect()
            rb0 = blocking_readbacks()
            hp0 = host_phase_seconds()
            t0 = time.perf_counter()
            with obs.cycle(cycle) as root:
                ssn = OpenSession(cache, tiers)
                t1 = time.perf_counter()
                act_times = []
                for name, act in acts:
                    a0 = time.perf_counter()
                    act.execute(ssn)
                    act_times.append((name, time.perf_counter() - a0))
                t2 = time.perf_counter()
                CloseSession(ssn)
            dt = time.perf_counter() - t0
            if os.environ.get("KB_BENCH_DEBUG"):
                per = " ".join(f"{n}={s:.3f}s" for n, s in act_times)
                print(f"steady {cycle}: open={t1 - t0:.3f}s {per} "
                      f"close={dt - (t2 - t0):.3f}s "
                      f"bound={len(binds) - before}", file=sys.stderr)
            latencies.append(dt)
            bound += len(binds) - before
            for name, secs in act_times:
                action_seconds[name] += secs
            readbacks.append(blocking_readbacks() - rb0)
            engines.append(_alloc_mod.last_cycle_engine)
            span_counts.append(root.count())
            trace_roots.append(root)
            hp = host_phase_seconds()
            for k in hp:
                phase_s.setdefault(k, []).append(hp[k] - hp0.get(k, 0.0))
        recompiles = recompiles_total() - recompiles0
        acct = readback_accounting(since=acct0)
    finally:
        gc.enable()
    action_ms = {name: round(1e3 * secs / max(1, len(latencies)), 3)
                 for name, secs in action_seconds.items()}
    # the steady host split (ISSUE 9): per-phase median ms per measured
    # cycle, straight off the update_host_phase keys — fold (snapshot
    # assembly off the event-folded base), apply (cache.bind_many column
    # ops), audit (lazy full-clone diff, 0 unless armed), next to the
    # legacy open/tensorize/replay/close. NOTE "fold" nests inside
    # "open" and "apply" inside "replay" — report, don't sum, nested keys
    phase_ms = {k: round(1e3 * float(np.median(v)), 3)
                for k, v in sorted(phase_s.items())}
    # peak RSS in MiB (ru_maxrss is KiB on Linux) — the soak evidence
    rss_mb = _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss / 1024.0
    return (latencies, bound, action_ms, readbacks, rss_mb, engines,
            recompiles, span_counts, trace_roots, phase_ms, acct)


def run_churn_ladder(config, cycles: int, mode: str,
                     levels=(256, 1024, 4096)):
    """Churn ladder (ISSUE 15): ONE persistent cache measured at each
    churn level ascending. The active-set engine picks its task grain
    from the pending count, so each level exercises one registered
    bucket (256 / 1024 / 4096).

    Warm-up traces every ladder shape before ``compilesvc.mark_warm()``:
    two unmeasured churn cycles per level, with the activeset cadence
    RESET at each level so the first engaged cycle is an audit cycle —
    that traces BOTH the steady packed entry and the combined audit
    entry at that grain. The cadence is reset again at the top of each
    measured window, so every emitted line carries at least one
    in-window audit cycle (p50 over >=9 cycles stays robust to it).

    Returns one dict per level: wall latencies, readbacks, engines,
    recompiles, and the ``activeset`` evidence block (engaged cycles,
    audits, divergences, demotions, median active tasks / candidate
    nodes off the device telemetry frame)."""
    import gc

    from kubebatch_tpu import actions, plugins  # noqa: F401
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.conf import shipped_tiers
    from kubebatch_tpu.framework import CloseSession, OpenSession
    from kubebatch_tpu.objects import PodPhase
    from kubebatch_tpu.sim import baseline_cluster

    tiers = shipped_tiers()
    sim = baseline_cluster(config)
    binds = {}
    fresh_binds = []

    class _B:
        def bind(self, pod, hostname):
            binds[pod.uid] = hostname
            pod.node_name = hostname
            fresh_binds.append(pod)

        def bind_many(self, pairs):
            for pod, hostname in pairs:
                binds[pod.uid] = hostname
                pod.node_name = hostname
                fresh_binds.append(pod)

        def evict(self, pod):
            pod.deletion_timestamp = 1.0

    seam = _B()
    cache = SchedulerCache(binder=seam, evictor=seam, async_writeback=False)
    sim.populate(cache)
    acts = build_actions(config, mode)

    def kubelet_tick():
        for pod in fresh_binds:
            if pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                cache.update_pod(pod, pod)
        fresh_binds.clear()

    def one_cycle():
        ssn = OpenSession(cache, tiers)
        for _, act in acts:
            act.execute(ssn)
        CloseSession(ssn)

    from kubebatch_tpu.kernels import activeset as _activeset

    gc.disable()
    try:
        # schedule the whole cluster (active set declines at full width
        # and the warm-up rides the full hier engine)
        for _ in range(2):
            one_cycle()
            kubelet_tick()
        # trace every ladder shape, ascending; the cadence reset makes
        # the first engaged cycle per level an audit cycle, so the
        # combined audit entry is traced at every grain the measured
        # window can dispatch
        for level in levels:
            _activeset.reset()
            for _ in range(2):
                kubelet_tick()
                sim.churn_tick(cache, level)
                one_cycle()
        from kubebatch_tpu import compilesvc
        from kubebatch_tpu.actions import allocate as _alloc_mod
        from kubebatch_tpu.metrics import (activeset_audits_total,
                                           activeset_cycles_total,
                                           activeset_demotions_total,
                                           activeset_divergences_total,
                                           blocking_readbacks,
                                           recompiles_total)
        from kubebatch_tpu.obs import telemetry as _obs_telemetry
        compilesvc.mark_warm()
        out = []
        for level in levels:
            _activeset.reset()
            rc0 = recompiles_total()
            ac0 = activeset_cycles_total()
            au0 = activeset_audits_total()
            dv0 = activeset_divergences_total()
            dm0 = activeset_demotions_total()
            latencies = []
            readbacks = []
            engines = []
            act_tasks = []
            act_nodes = []
            action_seconds = {name: 0.0 for name in CONFIG_ACTIONS[config]}
            bound = 0
            for cycle in range(cycles):
                before = len(binds)
                kubelet_tick()
                sim.churn_tick(cache, level)
                gc.collect()
                rb0 = blocking_readbacks()
                t0 = time.perf_counter()
                ssn = OpenSession(cache, tiers)
                for name, act in acts:
                    a0 = time.perf_counter()
                    act.execute(ssn)
                    action_seconds[name] += time.perf_counter() - a0
                CloseSession(ssn)
                dt = time.perf_counter() - t0
                if os.environ.get("KB_BENCH_DEBUG"):
                    print(f"ladder churn={level} cycle={cycle}: "
                          f"{dt:.3f}s bound={len(binds) - before} "
                          f"engine={_alloc_mod.last_cycle_engine}",
                          file=sys.stderr)
                latencies.append(dt)
                bound += len(binds) - before
                readbacks.append(blocking_readbacks() - rb0)
                engines.append(_alloc_mod.last_cycle_engine)
                if engines[-1] == "activeset":
                    frame = _obs_telemetry.last_frame("activeset")
                    if frame is not None:
                        act_tasks.append(frame.get("act_tasks", 0))
                        act_nodes.append(frame.get("act_nodes", 0))
            action_ms = {name: round(1e3 * s / max(1, len(latencies)), 3)
                         for name, s in action_seconds.items()}
            out.append({
                "churn_pods": level,
                "latencies": latencies,
                "bound": bound,
                "readbacks": readbacks,
                "engines": engines,
                "action_ms": action_ms,
                "recompiles": recompiles_total() - rc0,
                "activeset": {
                    "cycles": activeset_cycles_total() - ac0,
                    "audits": activeset_audits_total() - au0,
                    "divergences": activeset_divergences_total() - dv0,
                    "demotions": activeset_demotions_total() - dm0,
                    "active_tasks": int(np.median(act_tasks))
                    if act_tasks else 0,
                    "candidate_nodes": int(np.median(act_nodes))
                    if act_nodes else 0,
                },
            })
    finally:
        gc.enable()
    return out


def run_arrival(config, cycles: int, churn_pods: int,
                arrivals_per_cycle: int = 4) -> dict:
    """Schedule-on-arrival measurement (ISSUE 9): a steady churn regime
    driven through a REAL Scheduler with the sub-cycle armed; every
    measured cycle injects latency-lane pod arrivals between full
    cycles and records arrival -> decision latency through the
    sub-cycle (the lane's promise: a placement without waiting for the
    1 s schedule period).

    The cluster runs at ~70% fill, NOT the steady bench's 2x-
    oversubscribed baseline: schedule-on-arrival is a latency story for
    clusters with headroom — in a saturated cluster the arrival queues
    behind the backlog no matter how fast the solve is."""
    import dataclasses
    import gc

    from kubebatch_tpu import actions, plugins  # noqa: F401
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.metrics import (arrivals_observed_total,
                                       readback_accounting,
                                       recompiles_total,
                                       subcycles_total)
    from kubebatch_tpu.obs import ledger as ledger_mod
    from kubebatch_tpu.objects import (GROUP_NAME_ANNOTATION, Container,
                                       Pod, PodGroup, PodPhase,
                                       resource_list)
    from kubebatch_tpu.runtime.scheduler import (DEFAULT_SCHEDULER_CONF,
                                                 Scheduler)
    from kubebatch_tpu.runtime.subcycle import LANE_ANNOTATION
    from kubebatch_tpu.sim.cluster import BASELINE_SPECS, build_cluster

    spec = BASELINE_SPECS[config]
    cap_pods = min(
        spec.n_nodes * spec.node_cpu_millis // max(1, spec.pod_cpu_millis),
        spec.n_nodes * spec.node_mem_bytes // max(1, spec.pod_mem_bytes))
    fill_groups = max(2, int(0.7 * cap_pods)
                      // max(1, spec.pods_per_group))
    spec = dataclasses.replace(spec,
                               n_groups=min(spec.n_groups, fill_groups))
    sim = build_cluster(spec)
    binds = {}
    fresh_binds = []

    class _B:
        def bind(self, pod, hostname):
            binds[pod.uid] = hostname
            pod.node_name = hostname
            fresh_binds.append(pod)

        def bind_many(self, pairs):
            for pod, hostname in pairs:
                self.bind(pod, hostname)

        def evict(self, pod):
            pod.deletion_timestamp = 1.0

    cache = SchedulerCache(binder=_B(), evictor=_B(),
                           async_writeback=False)
    sim.populate(cache)
    actions_line = ", ".join(CONFIG_ACTIONS[config])
    conf = DEFAULT_SCHEDULER_CONF.replace(
        'actions: "allocate, backfill"', f'actions: "{actions_line}"')
    # schedule_period is irrelevant (cycles are driven manually); the
    # sub-cycle hook is the thing under test
    sched = Scheduler(cache, scheduler_conf=conf, schedule_period=3600.0,
                      subcycle=True)

    def kubelet_tick():
        for pod in fresh_binds:
            if pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                cache.update_pod(pod, pod)
        fresh_binds.clear()

    rush_seq = [0]
    #: live latency gangs: (inject_cycle, pg, pod). Latency-lane work is
    #: short-lived by nature (interactive/inference bursts), so gangs
    #: retire after ~2 cycles — WITHOUT this the running-task population
    #: grows monotonically through the window, walks across a shape-
    #: bucket boundary mid-measurement, and pays a counted recompile
    #: (victims/unregistered) that a stationary regime never sees
    rush_live = []

    def inject_arrival(cycle=None):
        """One latency-lane single-pod gang through the cache handlers —
        the arrival hook runs the sub-cycle inline on this thread."""
        gid = rush_seq[0]
        rush_seq[0] += 1
        pg = PodGroup(name=f"rush-{gid:05d}", namespace="sim",
                      min_member=1, queue=sim.queues[0].name,
                      creation_timestamp=2e9 + gid)
        cache.add_pod_group(pg)
        pod = Pod(name=f"{pg.name}-0", namespace="sim",
                  annotations={GROUP_NAME_ANNOTATION: pg.name,
                               LANE_ANNOTATION: "latency"},
                  containers=[Container(requests=resource_list(
                      cpu=spec.pod_cpu_millis,
                      memory=spec.pod_mem_bytes))],
                  creation_timestamp=2e9 + gid)
        cache.add_pod(pod)
        rush_live.append((cycle, pod.uid, pg, pod))

    def retire_rush(before_cycle):
        """Complete latency gangs injected before ``before_cycle``
        (None = retire everything, used between warm-up and the
        measured window)."""
        keep = []
        for c, uid, pg, pod in rush_live:
            if before_cycle is None or c is None or c < before_cycle:
                cache.delete_pod(pod)
                cache.delete_pod_group(pg)
            else:
                keep.append((c, uid, pg, pod))
        rush_live[:] = keep
        cache.process_cleanup_jobs()

    offered = [0]
    cycle_lat = []

    def drive_cycle(cycle, measure):
        """ONE iteration of the steady arrival regime — used verbatim
        for warm-up and measurement, so every shape the measured window
        can trace (steady churn, the rush-skewed reclaim/victim builds,
        the sub-cycle per-visit solve, gang retirement) is traced
        before the warm mark arms the recompile pin."""
        kubelet_tick()
        retire_rush(cycle - 1)
        sim.churn_tick(cache, churn_pods)
        gc.collect()
        t0 = time.perf_counter()
        sched.run_cycle()
        if measure:
            cycle_lat.append(time.perf_counter() - t0)
        kubelet_tick()
        for _ in range(arrivals_per_cycle):
            inject_arrival(cycle)
            if measure:
                offered[0] += 1
        kubelet_tick()

    gc.disable()
    try:
        # settle: schedule the initial backlog
        for _ in range(2):
            sched.run_cycle()
            kubelet_tick()
        # warm-up: 3 iterations of the measured regime itself
        for warm_cycle in range(3):
            drive_cycle(warm_cycle, measure=False)

        # from here a real compile is a COUNTED recompile (without the
        # warm mark the pin below would be vacuous)
        from kubebatch_tpu import compilesvc
        compilesvc.mark_warm()
        recompiles0 = recompiles_total()
        acct0 = readback_accounting()
        sub0 = subcycles_total()
        obs0 = arrivals_observed_total()
        # the measurement window over the decision ledger: percentiles
        # come from its streaming histogram (obs/ledger.py — no raw
        # latency list anywhere; a >4096-arrival run no longer truncates
        # the way the old ARRIVAL_STATS ring slice did)
        win = ledger_mod.window()
        for cycle in range(3, 3 + cycles):
            drive_cycle(cycle, measure=True)
        acct = readback_accounting(since=acct0)
        recompiles = recompiles_total() - recompiles0
        subcycles = subcycles_total() - sub0
        # decided = the exact monotonic counter delta; the ledger window
        # carries the shape
        n_new = arrivals_observed_total() - obs0
    finally:
        gc.enable()
    from kubebatch_tpu.metrics import recompiles_by_reason
    recompile_split = {f"{engine}/{reason}": n for (engine, reason), n
                       in recompiles_by_reason().items()}

    arr_p50 = win.subcycle_percentile(50) or 0.0
    arr_p99 = win.subcycle_percentile(99) or 0.0
    arr_max = win.subcycle_max_ms() or 0.0
    return {
        "metric": f"arrival_decision_p50_ms_cfg{config}",
        "value": round(arr_p50, 3),
        "unit": "ms",
        # vs the 1 s schedule period the lane would otherwise wait for
        "vs_baseline": round(1000.0 / max(arr_p99, 1e-9), 4),
        "arrival_p99_ms": round(arr_p99, 3),
        "arrival_max_ms": round(arr_max, 3),
        "arrivals_offered": offered[0],
        "arrivals_decided": n_new,
        "subcycles": subcycles,
        "churn_pods": churn_pods,
        "measured_cycles": cycles,
        "full_cycle_p50_ms": round(
            float(np.percentile(cycle_lat, 50)) * 1e3, 3),
        "recompiles_total": recompiles,
        "recompiles_by_reason": recompile_split,
        "readback_accounting": acct,
        "readbacks_per_decision": acct["readbacks_per_decision"],
    }


def run_sustained(config, cycles: int, mode: str,
                  churn_pods: int, trace: str = "") -> dict:
    """Sustained-rate A/B (ISSUE 16): the SAME steady churn regime
    driven through a real Scheduler twice in one process — sequential
    loop first, then the pipelined executor (runtime/pipeline.py) —
    and reported as cycles/s + pods-bound/s at saturation instead of
    per-cycle p50. The sequential loop's wall per cycle is
    host_work + flight (the blocking readback pins the solve to the
    critical path); the pipelined loop's is max(host_work, flight), so
    the sustained rate is where the overlap shows up.

    Alongside the rate: arrival -> decision p50/p99 through the cache
    arrival hooks (a churned pod's wait from add_pod to its bind
    write-back — under overlap a decision lands one cycle late, so
    this is the honesty figure next to the cps win), and the
    readback_accounting split that REPLACES the 1-readback-per-cycle
    pin: the pipelined arm must show ZERO blocking readbacks per
    decision (the critical-path figure) while total_readbacks_per_
    decision still proves one transfer per solve happened — deferred,
    off the critical path."""
    import gc

    from kubebatch_tpu import actions, compilesvc, plugins  # noqa: F401
    from kubebatch_tpu.actions import allocate as _alloc_mod
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.metrics import (pipeline_conflicts_by_outcome,
                                       pipeline_conflicts_total,
                                       pipeline_cycles_total,
                                       pipeline_demotions_total,
                                       readback_accounting,
                                       recompiles_total)
    from kubebatch_tpu.objects import PodPhase
    from kubebatch_tpu.runtime import pipeline as pipeline_mod
    from kubebatch_tpu.runtime.scheduler import (DEFAULT_SCHEDULER_CONF,
                                                 Scheduler)
    from kubebatch_tpu.sim import baseline_cluster

    actions_line = ", ".join(CONFIG_ACTIONS[config])
    conf = DEFAULT_SCHEDULER_CONF.replace(
        'actions: "allocate, backfill"', f'actions: "{actions_line}"')
    # both arms run the engine family the executor pipelines (the
    # persistent-carry activeset/hier path); auto would hand small
    # configs to the flat engines and measure nothing. Same env for
    # both arms — the A/B stays apples-to-apples.
    solver = mode if mode in ("hier", "activeset") else "activeset"
    saved_solver = os.environ.get("KUBEBATCH_SOLVER")

    def run_arm(pipelined: bool) -> dict:
        from kubebatch_tpu.obs import ledger as ledger_mod

        sim = baseline_cluster(config)
        binds = {}
        fresh_binds = []

        class _B:
            def bind(self, pod, hostname):
                binds[pod.uid] = hostname
                pod.node_name = hostname
                fresh_binds.append(pod)

            def bind_many(self, pairs):
                for pod, hostname in pairs:
                    binds[pod.uid] = hostname
                    pod.node_name = hostname
                    fresh_binds.append(pod)

            def evict(self, pod):
                pod.deletion_timestamp = 1.0

        seam = _B()
        cache = SchedulerCache(binder=seam, evictor=seam,
                               async_writeback=False)
        sim.populate(cache)
        pipeline_mod.reset()
        sched = Scheduler(cache, scheduler_conf=conf,
                          schedule_period=3600.0, pipeline=pipelined)

        replayer = None
        if trace:
            # --trace: both arms replay the SAME trace stream (fresh
            # replayer per arm, identical records) so the A/B stays
            # apples-to-apples under the workload plane's shapes
            from kubebatch_tpu.workloads import TraceReplayer
            _, records, dt = _build_trace(
                trace, target_pods=4 * max(1, churn_pods),
                cycles=cycles + 8, lifetime_cycles=8,
                cpu_milli=sim.spec.pod_cpu_millis,
                mem_bytes=sim.spec.pod_mem_bytes,
                n_queues=max(1, len(sim.queues)))
            replayer = TraceReplayer(records, _DirectEmitter(cache),
                                     [q.name for q in sim.queues],
                                     dt=dt)

        def churn():
            if replayer is not None:
                replayer.tick()
            else:
                sim.churn_tick(cache, churn_pods)

        def kubelet_tick():
            for pod in fresh_binds:
                if pod.phase == PodPhase.PENDING:
                    pod.phase = PodPhase.RUNNING
                    cache.update_pod(pod, pod)
            fresh_binds.clear()

        gc.disable()
        try:
            for _ in range(2):          # settle the initial backlog
                sched.run_cycle()
                kubelet_tick()
            for _ in range(3):          # trace every steady churn shape
                kubelet_tick()
                churn()
                sched.run_cycle()
                kubelet_tick()
            compilesvc.mark_warm()
            rc0 = recompiles_total()
            acct0 = readback_accounting()
            pc0 = pipeline_cycles_total()
            cf0 = pipeline_conflicts_total()
            dm0 = pipeline_demotions_total()
            engines = set()
            bound0 = len(binds)
            # arrival -> decision latency through the decision ledger:
            # the cache stamps every pending arrival at ingestion and
            # closes the record at the bind state flip — the window
            # diffs its streaming histograms over exactly the measured
            # cycles (the hand-rolled arrive_ts/bind_ts dicts this
            # replaced gated on a measuring flag the same way)
            win = ledger_mod.window()
            gc.collect()
            t0 = time.perf_counter()
            for _ in range(cycles):
                kubelet_tick()
                churn()
                sched.run_cycle()
                engines.add(_alloc_mod.last_cycle_engine)
                kubelet_tick()
            if pipelined and sched._pipeline is not None:
                # the last dispatched solve must land inside the timed
                # window — cps is rate of COMPLETED scheduling work
                sched._pipeline.drain()
                kubelet_tick()
            wall = time.perf_counter() - t0
            acct = readback_accounting(since=acct0)
            recompiles = recompiles_total() - rc0
        finally:
            gc.enable()
        return {
            "cps": cycles / wall if wall else 0.0,
            "pods_bound_per_sec": (len(binds) - bound0) / wall
            if wall else 0.0,
            "wall_s": round(wall, 3),
            "arrival_decision_p50_ms": round(
                win.percentile(50) or 0.0, 3),
            "arrival_decision_p99_ms": round(
                win.percentile(99) or 0.0, 3),
            "arrivals_decided": win.closed(),
            "ledger_deferred_closed": win.deferred_closed(),
            "engines": sorted(engines),
            "recompiles": recompiles,
            "readback_accounting": acct,
            "pipeline": {
                "cycles": pipeline_cycles_total() - pc0,
                "conflicts": pipeline_conflicts_total() - cf0,
                "conflicts_by_outcome": pipeline_conflicts_by_outcome(),
                "demotions": pipeline_demotions_total() - dm0,
                "demoted": pipeline_mod.demoted(),
            },
        }

    os.environ["KUBEBATCH_SOLVER"] = solver
    try:
        seq = run_arm(False)
        pipe = run_arm(True)
    finally:
        if saved_solver is None:
            os.environ.pop("KUBEBATCH_SOLVER", None)
        else:
            os.environ["KUBEBATCH_SOLVER"] = saved_solver
    speedup = (pipe["cps"] / seq["cps"]) if seq["cps"] else 0.0
    p_acct = pipe["readback_accounting"]
    return {
        "metric": f"sched_sustained_cps_cfg{config}_churn{churn_pods}",
        "value": round(pipe["cps"], 3),
        "unit": "cycles/s",
        "vs_baseline": round(speedup, 4),
        "speedup_vs_sequential": round(speedup, 4),
        "sequential_cps": round(seq["cps"], 3),
        "churn_pods": churn_pods,
        "measured_cycles": cycles,
        "sequential": seq,
        "pipeline": pipe,
        # the headline pins (enforced in main): overlap must not cost
        # correctness machinery — zero recompiles, zero demotions, and
        # the blocking-readback term GONE from the pipelined arm
        "recompiles_total": seq["recompiles"] + pipe["recompiles"],
        "pipeline_demotions": pipe["pipeline"]["demotions"],
        "readbacks_per_decision": p_acct["readbacks_per_decision"],
        "deferred_readbacks": p_acct["deferred_readbacks"],
        # the ledger evidence (ISSUE 17): decided counts and the
        # pipelined arm's arrival -> bind p99 now come from the decision
        # ledger's streaming histograms (bench_regression requires them)
        "ledger": {
            "decided": pipe["arrivals_decided"],
            "deferred_closed": pipe["ledger_deferred_closed"],
            "arrival_decision_p50_ms": pipe["arrival_decision_p50_ms"],
            "arrival_decision_p99_ms": pipe["arrival_decision_p99_ms"],
        },
    }


# ---------------------------------------------------------------------
# trace-replay workloads (ISSUE 19): --trace <preset|path> swaps the
# synthetic churn templates for the workloads/ plane — diurnal+heavy-
# tail arrival streams, elastic gangs, and a lendable backfill stream
# ---------------------------------------------------------------------

class _DirectEmitter:
    """StreamingEventSource facade that applies replayer events straight
    to the cache — synchronous trace churn for the steady/sustained
    arms (the soak runs the REAL source + watch pump; run_trace_soak)."""

    def __init__(self, cache):
        self._cache = cache

    def emit_group(self, pg):
        self._cache.add_pod_group(pg)

    def emit_group_update(self, old, new):
        self._cache.update_pod_group(old, new)

    def emit_group_delete(self, pg):
        self._cache.delete_pod_group(pg)

    def emit_pod(self, pod):
        self._cache.add_pod(pod)

    def emit_pod_update(self, old, new):
        self._cache.update_pod(old, new)

    def emit_pod_delete(self, pod):
        self._cache.delete_pod(pod)


def _build_trace(trace_arg: str, *, target_pods: int, cycles: int,
                 lifetime_cycles: int, cpu_milli, mem_bytes,
                 n_queues: int, seed: int = 0):
    """Resolve ``--trace`` into ``(label, records, dt)``.

    A preset name generates a seeded stream CALIBRATED to the caller's
    cluster: pod shapes match the cluster spec, ``dt`` (sim-seconds per
    scheduler cycle) is sized so a mean gang lives ~``lifetime_cycles``
    cycles, and the arrival rate is scaled (Little's law: concurrent
    tasks ~= rate x mean_tasks x mean_duration) so the steady-state
    concurrent trace pods land near ``target_pods``. A filesystem path
    replays a JSONL trace VERBATIM — shapes as recorded, ``dt`` sized
    so the file's span fits the run."""
    import dataclasses as _dc

    from kubebatch_tpu.workloads import (PRESETS, generate_trace,
                                         load_trace)
    if trace_arg in PRESETS:
        tspec = _dc.replace(PRESETS[trace_arg],
                            cpu_milli=float(cpu_milli),
                            mem_bytes=float(mem_bytes),
                            n_queues=max(1, n_queues))
        dt = tspec.mean_duration / max(1, lifetime_cycles)
        steady = (tspec.rate.base * tspec.mean_tasks
                  * tspec.mean_duration)
        tspec = tspec.scale_rate(target_pods / max(1e-9, steady))
        # +25% horizon: the warm-up/settle cycles ride the same stream
        records = generate_trace(tspec, seed, cycles * dt * 1.25)
        return trace_arg, records, dt
    if os.path.exists(trace_arg):
        records = load_trace(trace_arg)
        span = max((r.t for r in records), default=0.0) + 1.0
        return (os.path.basename(trace_arg), records,
                span / max(1, cycles))
    raise SystemExit(f"--trace {trace_arg!r}: not a preset "
                     f"({sorted(PRESETS)}) and no such file")


def _warm_trace_shape_grid(cache, source, sched, records, high_t, high_j,
                           queue_names, kubelet_tick, reap_evictions,
                           binds):
    """Trace the (t_pad, j_pad) bucket grid around the replay's observed
    backlog envelope BEFORE compilesvc.mark_warm, so the measured window
    recompiles nothing (the soak pin).

    A trace's pending backlog ramps/peaks with the diurnal wave (and
    snowballs in reclaim-limited congestion), so warm cycles at the
    stream's head only trace the smallest buckets — every bucket combo
    first crossed mid-window was a counted "unregistered" recompile.
    ``high_t``/``high_j`` are the max pending tasks/gangs the shape
    dry-run (a full pre-warm replay of the same stream) saw; the grid
    covers every pow2 rung up to those marks plus margin by injecting a
    synthetic pending backlog of exactly each rung's size, running one
    cycle, and deleting the synthetics. Sticky pad holds
    (cache.pad_sticky) are cleared per rung so each rung pads exactly;
    the j = 2t rungs — only reachable live via a post-warm-frozen
    one-below job hold — are manufactured by pre-seeding that hold.
    Rungs the live backlog already passed are skipped; already-traced
    rungs are jit cache hits (and persistent-cache retrievals across
    processes), so repeat runs pay near nothing."""
    from kubebatch_tpu.api import TaskStatus
    from kubebatch_tpu.kernels.tensorize import pad_to_bucket
    from kubebatch_tpu.objects import (Container, GROUP_NAME_ANNOTATION,
                                       Pod, PodGroup, resource_list)

    if not records:
        return
    # margin over the dry-run's high-water: the measured pass is not
    # bit-identical (armed fault seams, elastic-grow timing, cycle-phase
    # jitter), so cover one growth step past everything observed
    t_top = pad_to_bucket(max(8, int(high_t * 1.5) + 8), 8)
    j_top = pad_to_bucket(max(4, int(high_j * 1.5) + 4), 4)
    cpu, mem = records[0].cpu_milli, records[0].mem_bytes
    t_buckets = []
    b = 8
    while b <= t_top:
        t_buckets.append(b)
        b *= 2
    j_buckets = []
    b = 4
    while b <= min(j_top, 2 * t_top):
        j_buckets.append(b)
        b *= 2
    serial = [0]

    def pending_now():
        with cache._lock:
            pt = sum(len(j.task_status_index.get(TaskStatus.PENDING, {}))
                     for j in cache.jobs.values())
            pj = sum(1 for j in cache.jobs.values()
                     if j.task_status_index.get(TaskStatus.PENDING))
        return pt, pj

    for tb in t_buckets:
        for jb in j_buckets:
            if jb > 2 * tb:
                continue
            pend_t, pend_j = pending_now()
            if jb == 2 * tb:
                # j_pad = 2 x t_pad exists live only as a frozen
                # one-below hold; seed the hold and fill to one-below
                n_tasks, n_jobs = tb, tb
                cache.pad_sticky["cycle_jobs"] = [jb, 0]
            else:
                n_tasks, n_jobs = tb, jb
                cache.pad_sticky.pop("cycle_jobs", None)
            cache.pad_sticky.pop("cycle_tasks", None)
            add_t, add_j = n_tasks - pend_t, n_jobs - pend_j
            if add_t <= 0 or add_j <= 0 or add_t < add_j:
                continue        # live backlog already past this rung
            groups, pods = [], []
            base, extra = divmod(add_t, add_j)
            for g in range(add_j):
                serial[0] += 1
                pg = PodGroup(
                    name=f"warmgrid-{serial[0]:04d}", namespace="sim",
                    min_member=1,
                    queue=(queue_names[serial[0] % len(queue_names)]
                           if queue_names else ""),
                    creation_timestamp=1.5e9 + serial[0])
                source.emit_group(pg)
                groups.append(pg)
                for k in range(base + (1 if g < extra else 0)):
                    pod = Pod(
                        name=f"{pg.name}-{k:03d}", namespace="sim",
                        annotations={GROUP_NAME_ANNOTATION: pg.name},
                        containers=[Container(requests=resource_list(
                            cpu=cpu, memory=mem))],
                        creation_timestamp=1.5e9 + serial[0] + k / 1e3)
                    source.emit_pod(pod)
                    pods.append(pod)
            source.sync(timeout=30.0)
            sched.run_cycle()
            for pod in pods:
                binds.pop(pod.uid, None)
                source.emit_pod_delete(pod)
            for pg in groups:
                source.emit_group_delete(pg)
            kubelet_tick()      # replayer-owned binds only; clears fresh
            source.sync(timeout=30.0)
            reap_evictions()
    cache.pad_sticky.pop("cycle_tasks", None)
    cache.pad_sticky.pop("cycle_jobs", None)


def run_trace_soak(config, cycles: int, trace: str,
                   timeline_dir: str = "") -> dict:
    """Trace-replay soak (ISSUE 19 / ROADMAP item 3): the long-horizon
    soak harness of run_soak driven by the workloads/ plane instead of
    the synthetic churn templates — a live StreamingEventSource pump, a
    diurnal+heavy-tail gang stream with elastic resizes and a lendable
    backfill cohort, chaos count-seams armed mid-window (cache.fold +
    workload.elastic), and the backfill-over-reserved machinery on
    (KUBEBATCH_RESERVED_BACKFILL): the cluster runs ~50% static fill +
    ~35% steady trace load, so diurnal peaks and cron bursts create the
    contention that makes elastic gangs AlmostReady, lends their
    reserved capacity to backfill pods, and reclaims it atomically.

    The evidence line carries the run_soak SLO/timeline/ledger block
    PLUS the trace census (arrivals/completions/elastic_events), the
    peak lent capacity (backfilled_peak_milli), the backfill-over-
    reserved ledger, the in-soak audit-divergence count, and the
    injected-seam census. The caller (main) hard-fails on any breach,
    drift, recompile, audit divergence, nonzero guard counter
    (double-bind / lost-reservation), or a soak that never exercised
    the over-reserve/reclaim path."""
    import gc

    from kubebatch_tpu import actions, compilesvc, faults, plugins  # noqa: F401
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.metrics import (audit_failures_total,
                                       backfill_double_binds_total,
                                       backfill_over_placements_total,
                                       backfill_reclaims_total,
                                       backfill_tenants_evicted_total,
                                       lost_reservations_total,
                                       readback_accounting,
                                       recompiles_total,
                                       slo_breaches_by_objective,
                                       slo_breaches_total,
                                       timeline_drift_by_kind,
                                       timeline_drift_total)
    from kubebatch_tpu.obs import ledger as ledger_mod
    from kubebatch_tpu.obs import slo as slo_mod
    from kubebatch_tpu.obs import timeline as timeline_mod
    from kubebatch_tpu.runtime.scheduler import (DEFAULT_SCHEDULER_CONF,
                                                 Scheduler)
    from kubebatch_tpu.sim.cluster import BASELINE_SPECS, build_cluster
    from kubebatch_tpu.sim.source import StreamingEventSource
    from kubebatch_tpu.workloads import TraceReplayer
    import dataclasses as _dc

    spec = BASELINE_SPECS[config]
    cap_pods = int(min(
        spec.n_nodes * spec.node_cpu_millis
        // max(1, spec.pod_cpu_millis),
        spec.n_nodes * spec.node_mem_bytes
        // max(1, spec.pod_mem_bytes)))
    spec = _dc.replace(spec, n_groups=0, running_fill=0.5)
    label, records, dt = _build_trace(
        trace, target_pods=int(0.35 * cap_pods), cycles=cycles,
        lifetime_cycles=max(8, min(500, cycles // 20)),
        cpu_milli=spec.pod_cpu_millis, mem_bytes=spec.pod_mem_bytes,
        n_queues=max(1, spec.n_queues))

    # the workload plane exists to exercise backfill-over-reserved: a
    # trace line always arms the backfill action, even on configs whose
    # synthetic scenario is allocate-only
    acts = tuple(CONFIG_ACTIONS[config])
    if "backfill" not in acts:
        acts = acts + ("backfill",)
    conf = DEFAULT_SCHEDULER_CONF.replace(
        'actions: "allocate, backfill"',
        f'actions: "{", ".join(acts)}"')

    sim = build_cluster(spec)
    binds = {}
    fresh_binds = []
    evicted_uids = []

    class _B:
        def bind(self, pod, hostname):
            binds[pod.uid] = hostname
            pod.node_name = hostname
            fresh_binds.append(pod)

        def bind_many(self, pairs):
            for pod, hostname in pairs:
                self.bind(pod, hostname)

        def evict(self, pod):
            # a reclaimed backfill tenant: the write-back records the
            # eviction; the "cluster" answers with a pod delete after
            # the cycle (reap_evictions)
            evicted_uids.append(pod.uid)

    seam = _B()
    saved_bf = os.environ.get("KUBEBATCH_RESERVED_BACKFILL")
    os.environ["KUBEBATCH_RESERVED_BACKFILL"] = "1"
    cache = SchedulerCache(binder=seam, evictor=seam,
                           async_writeback=False)
    source = StreamingEventSource()
    with source._lock:
        for q in sim.queues:
            source.queues[q.name] = q
        for n in sim.nodes:
            source.nodes[n.name] = n
        for g in sim.groups:
            source.groups[f"{g.namespace}/{g.name}"] = g
        for p in sim.pods:
            source.pods[f"{p.namespace}/{p.name}"] = p
    source.start(cache)
    replayer = TraceReplayer(records, source,
                             [q.name for q in sim.queues], dt=dt)
    # audit_every: the fold-vs-full-clone snapshot diff runs INSIDE the
    # soak — trace churn exercising the fold/audit rungs is the point
    sched = Scheduler(cache, scheduler_conf=conf,
                      schedule_period=3600.0, audit_every=50)

    def kubelet_tick():
        replayer.kubelet(fresh_binds)
        fresh_binds.clear()

    def reap_evictions():
        while evicted_uids:
            uid = evicted_uids.pop()
            binds.pop(uid, None)
            replayer.kill_pod(uid)

    # chaos count-seams mid-window: cache.fold proves the fold demotion
    # rung lands under trace churn; workload.elastic forces one
    # mid-flight grow through the replayer. Seams that would trip the
    # soak's own pins by design (obs.slo fires a synthetic breach;
    # device seams force engine recompiles) stay off THIS plan — the
    # full randomized schedule is the chaos line's job.
    plan = faults.FaultPlan(rates={}, counts={"cache.fold": 1,
                                              "workload.elastic": 1},
                            seed=0)
    fault_start = max(10, cycles // 10)
    fault_stop = max(fault_start + 1, cycles // 2)

    cycle_hist = ledger_mod.StreamHist()
    backfilled_peak = 0.0
    gc.disable()
    try:
        for _ in range(2):              # settle: adopt the fill
            source.sync(timeout=30.0)
            sched.run_cycle()
            kubelet_tick()
        # shape dry-run: replay the IDENTICAL stream once pre-warm, so
        # every (t_pad, j_pad) signature the measured window dispatches
        # is traced for free — including the congestion regimes no host
        # model predicts (reclaim-limited backlog snowballs). The high-
        # water pending counts observed here size the grid pass below.
        from kubebatch_tpu.api import TaskStatus as _TS
        high_t = high_j = 0
        for _ in range(cycles):
            kubelet_tick()
            replayer.tick()
            source.sync(timeout=30.0)
            with cache._lock:
                pt = sum(len(j.task_status_index.get(_TS.PENDING, {}))
                         for j in cache.jobs.values())
                pj = sum(1 for j in cache.jobs.values()
                         if j.task_status_index.get(_TS.PENDING))
            high_t, high_j = max(high_t, pt), max(high_j, pj)
            sched.run_cycle()
            kubelet_tick()
            reap_evictions()
            if replayer.exhausted:
                break
        # teardown: the dry-run's survivors leave the stage, and a fresh
        # replayer over the same records drives the measured window from
        # the same near-empty cluster the dry-run started from
        for pod in list(replayer.pods_by_uid.values()):
            binds.pop(pod.uid, None)
            source.emit_pod_delete(pod)
        for gang in list(replayer.live.values()):
            source.emit_group_delete(gang.pg)
        fresh_binds.clear()
        del evicted_uids[:]
        source.sync(timeout=30.0)
        replayer = TraceReplayer(records, source,
                                 [q.name for q in sim.queues], dt=dt)
        for _ in range(2):              # settle the emptied cluster
            source.sync(timeout=30.0)
            sched.run_cycle()
            kubelet_tick()
        # the dry-run traces the shapes its own trajectory crossed; the
        # grid covers the whole bucket lattice up to that high-water
        # plus margin, so measured-pass divergence (armed fault seams,
        # elastic timing) cannot reach an untraced rung (the soak pins
        # recompiles_total at 0 across the whole measured window)
        _warm_trace_shape_grid(
            cache, source, sched, records, high_t=high_t, high_j=high_j,
            queue_names=[q.name for q in sim.queues],
            kubelet_tick=kubelet_tick, reap_evictions=reap_evictions,
            binds=binds)
        compilesvc.mark_warm()
        rc0 = recompiles_total()
        acct0 = readback_accounting()
        slo0 = slo_breaches_total()
        drift0 = timeline_drift_total()
        audit0 = audit_failures_total()
        bf0 = {"over": backfill_over_placements_total(),
               "reclaims": backfill_reclaims_total(),
               "evicted": backfill_tenants_evicted_total(),
               "double": backfill_double_binds_total(),
               "lost": lost_reservations_total()}
        stats0 = dict(replayer.stats)
        import dataclasses as _dcr
        timeline_mod.arm(timeline_dir or None)
        # same saturation-calibrated arrival floor as run_soak: peak
        # contention queues gangs for seconds by design
        slo_mod.arm(tuple(
            _dcr.replace(o, threshold_ms=max(o.threshold_ms, 60000.0))
            if o.name == "arrival_decision_p99" else o
            for o in slo_mod.DEFAULT_OBJECTIVES))
        win = ledger_mod.window()
        gc.collect()
        t0 = time.perf_counter()
        for cycle in range(cycles):
            if cycle == fault_start:
                faults.arm(plan)
            if cycle == fault_stop:
                faults.disarm()
            kubelet_tick()
            replayer.tick()
            replayer.inject_elastic()
            source.sync(timeout=30.0)
            c0 = time.perf_counter()
            sched.run_cycle()
            cycle_hist.observe(time.perf_counter() - c0)
            kubelet_tick()
            reap_evictions()
            with cache._lock:
                lent = sum(n.backfilled.milli_cpu
                           for n in cache.nodes.values())
            backfilled_peak = max(backfilled_peak, lent)
        wall = time.perf_counter() - t0
        acct = readback_accounting(since=acct0)
        recompiles = recompiles_total() - rc0
    finally:
        faults.disarm()
        gc.enable()
        timeline_mod.flush()
        tstats = timeline_mod.stats()
        slo_snap = slo_mod.snapshot()
        slo_mod.disarm()
        timeline_mod.disarm()
        source.stop()
        if saved_bf is None:
            os.environ.pop("KUBEBATCH_RESERVED_BACKFILL", None)
        else:
            os.environ["KUBEBATCH_RESERVED_BACKFILL"] = saved_bf

    _, _, cyc_buckets = cycle_hist.snapshot()
    breaches = slo_breaches_total() - slo0
    drift = timeline_drift_total() - drift0
    stats = {k: replayer.stats[k] - stats0[k] for k in replayer.stats}
    out = {
        "metric": (f"sched_soak_cfg{config}_cycles{cycles}"
                   f"_trace_{label}"),
        "value": round(cycles / wall, 3) if wall else 0.0,
        "unit": "cycles/s",
        "vs_baseline": round(cycles / wall, 4) if wall else 0.0,
        "measured_cycles": cycles,
        "wall_s": round(wall, 3),
        "trace_preset": label,
        "trace_dt_s": round(dt, 3),
        "trace_records": len(records),
        "trace": stats,
        "elastic_events": stats["elastic_events"],
        "backfilled_peak_milli": round(backfilled_peak, 1),
        "backfill": {
            "over_placements":
                backfill_over_placements_total() - bf0["over"],
            "reclaims": backfill_reclaims_total() - bf0["reclaims"],
            "tenants_evicted":
                backfill_tenants_evicted_total() - bf0["evicted"],
            "double_binds":
                backfill_double_binds_total() - bf0["double"],
            "lost_reservations":
                lost_reservations_total() - bf0["lost"],
        },
        "audit_divergences": audit_failures_total() - audit0,
        "faults_injected": sum(plan.injected.values()),
        "faults_by_seam": dict(plan.injected),
        "cycle_p50_ms": round(
            (ledger_mod._pct_from_counts(cyc_buckets, 50) or 0.0) * 1e3,
            3),
        "cycle_p99_ms": round(
            (ledger_mod._pct_from_counts(cyc_buckets, 99) or 0.0) * 1e3,
            3),
        "slo_report": {
            "breaches_total": breaches,
            "by_objective": slo_breaches_by_objective(),
            "objectives": [
                {"name": o["name"],
                 "breached": o["breached"],
                 "fast_burn": o["windows"]["fast"]["burn"],
                 "slow_burn": o["windows"]["slow"]["burn"]}
                for o in slo_snap.get("objectives", [])],
        },
        "timeline_drift_total": drift,
        "timeline_drift_by_kind": timeline_drift_by_kind(),
        "timeline": {
            "path": (timeline_mod.TIMELINE.path or ""),
            "ticks": tstats["ticks"],
            "spilled": tstats["spilled"],
            "ring": tstats["ring"],
            "rss_mb_fast": tstats["rss_mb_fast"],
            "rss_mb_slow": tstats["rss_mb_slow"],
            "cycle_ms_fast": tstats["cycle_ms_fast"],
            "cycle_ms_slow": tstats["cycle_ms_slow"],
        },
        "recompiles_total": recompiles,
        "ledger": {
            "decided": win.closed(),
            "arrival_decision_p50_ms": round(win.percentile(50) or 0.0,
                                             3),
            "arrival_decision_p99_ms": round(win.percentile(99) or 0.0,
                                             3),
        },
        "readback_accounting": acct,
        "readbacks_per_decision": acct["readbacks_per_decision"],
    }
    return out


def run_soak(config, cycles: int, churn_pods: int,
             timeline_dir: str = "", trace: str = "") -> dict:
    """Long-horizon soak (ISSUE 17): one steady churn regime driven for
    ``cycles`` scheduler cycles (default 10k from the CLI) with the SLO
    burn-rate plane armed on the shipped objectives and the timeline
    spilling per-cycle digests to ``timeline_dir`` — a multi-hour run
    produces a replayable JSONL record at O(1) resident memory, and the
    evidence line carries the SLO report, the drift counter and the
    ledger percentiles. The caller (main) hard-exits on any breach,
    drift firing, or measured-window recompile.

    With ``trace`` set (``--trace <preset|path>``) the whole regime is
    delegated to the workloads/ plane — see run_trace_soak."""
    if trace:
        return run_trace_soak(config, cycles, trace,
                              timeline_dir=timeline_dir)
    import gc

    from kubebatch_tpu import actions, compilesvc, plugins  # noqa: F401
    from kubebatch_tpu.cache import SchedulerCache
    from kubebatch_tpu.metrics import (readback_accounting,
                                       recompiles_total,
                                       slo_breaches_by_objective,
                                       slo_breaches_total,
                                       timeline_drift_by_kind,
                                       timeline_drift_total)
    from kubebatch_tpu.objects import PodPhase
    from kubebatch_tpu.obs import ledger as ledger_mod
    from kubebatch_tpu.obs import slo as slo_mod
    from kubebatch_tpu.obs import timeline as timeline_mod
    from kubebatch_tpu.runtime.scheduler import (DEFAULT_SCHEDULER_CONF,
                                                 Scheduler)
    from kubebatch_tpu.sim import baseline_cluster

    actions_line = ", ".join(CONFIG_ACTIONS[config])
    conf = DEFAULT_SCHEDULER_CONF.replace(
        'actions: "allocate, backfill"', f'actions: "{actions_line}"')
    sim = baseline_cluster(config)
    binds = {}
    fresh_binds = []

    class _B:
        def bind(self, pod, hostname):
            binds[pod.uid] = hostname
            pod.node_name = hostname
            fresh_binds.append(pod)

        def bind_many(self, pairs):
            for pod, hostname in pairs:
                self.bind(pod, hostname)

        def evict(self, pod):
            pod.deletion_timestamp = 1.0

    seam = _B()
    cache = SchedulerCache(binder=seam, evictor=seam,
                           async_writeback=False)
    sim.populate(cache)
    sched = Scheduler(cache, scheduler_conf=conf, schedule_period=3600.0)

    def kubelet_tick():
        for pod in fresh_binds:
            if pod.phase == PodPhase.PENDING:
                pod.phase = PodPhase.RUNNING
                cache.update_pod(pod, pod)
        fresh_binds.clear()

    cycle_hist = ledger_mod.StreamHist()   # O(1) cycle-wall record
    gc.disable()
    try:
        for _ in range(2):              # settle the initial backlog
            sched.run_cycle()
            kubelet_tick()
        for _ in range(3):              # trace every steady churn shape
            kubelet_tick()
            sim.churn_tick(cache, churn_pods)
            sched.run_cycle()
            kubelet_tick()
        compilesvc.mark_warm()
        rc0 = recompiles_total()
        acct0 = readback_accounting()
        slo0 = slo_breaches_total()
        drift0 = timeline_drift_total()
        # the observability planes under test: cycle-hooked SLO
        # evaluation + the spilling timeline (window state fresh from
        # here — pre-arm history never counts into a burn window). The
        # baseline cluster is 2x oversubscribed, so churned gangs queue
        # behind the backlog for seconds BY DESIGN — the arrival
        # objective gets a saturation-calibrated floor (the headroom
        # regimes keep the production 5 s bound); relative latency rot
        # is the timeline drift rung's job
        import dataclasses as _dc
        timeline_mod.arm(timeline_dir or None)
        slo_mod.arm(tuple(
            _dc.replace(o, threshold_ms=max(o.threshold_ms, 60000.0))
            if o.name == "arrival_decision_p99" else o
            for o in slo_mod.DEFAULT_OBJECTIVES))
        win = ledger_mod.window()
        gc.collect()
        t0 = time.perf_counter()
        for _ in range(cycles):
            kubelet_tick()
            sim.churn_tick(cache, churn_pods)
            c0 = time.perf_counter()
            sched.run_cycle()
            cycle_hist.observe(time.perf_counter() - c0)
            kubelet_tick()
        wall = time.perf_counter() - t0
        acct = readback_accounting(since=acct0)
        recompiles = recompiles_total() - rc0
    finally:
        gc.enable()
        timeline_mod.flush()
        tstats = timeline_mod.stats()
        slo_snap = slo_mod.snapshot()
        slo_mod.disarm()
        timeline_mod.disarm()

    _, _, cyc_buckets = cycle_hist.snapshot()
    breaches = slo_breaches_total() - slo0
    drift = timeline_drift_total() - drift0
    out = {
        "metric": f"sched_soak_cfg{config}_cycles{cycles}",
        "value": round(cycles / wall, 3) if wall else 0.0,
        "unit": "cycles/s",
        # vs the 1 cycle/s north-star budget
        "vs_baseline": round(cycles / wall, 4) if wall else 0.0,
        "measured_cycles": cycles,
        "churn_pods": churn_pods,
        "wall_s": round(wall, 3),
        "cycle_p50_ms": round(
            (ledger_mod._pct_from_counts(cyc_buckets, 50) or 0.0) * 1e3,
            3),
        "cycle_p99_ms": round(
            (ledger_mod._pct_from_counts(cyc_buckets, 99) or 0.0) * 1e3,
            3),
        "slo_report": {
            "breaches_total": breaches,
            "by_objective": slo_breaches_by_objective(),
            "objectives": [
                {"name": o["name"],
                 "breached": o["breached"],
                 "fast_burn": o["windows"]["fast"]["burn"],
                 "slow_burn": o["windows"]["slow"]["burn"]}
                for o in slo_snap.get("objectives", [])],
        },
        "timeline_drift_total": drift,
        "timeline_drift_by_kind": timeline_drift_by_kind(),
        "timeline": {
            "path": (timeline_mod.TIMELINE.path or ""),
            "ticks": tstats["ticks"],
            "spilled": tstats["spilled"],
            "ring": tstats["ring"],
            "rss_mb_fast": tstats["rss_mb_fast"],
            "rss_mb_slow": tstats["rss_mb_slow"],
            "cycle_ms_fast": tstats["cycle_ms_fast"],
            "cycle_ms_slow": tstats["cycle_ms_slow"],
        },
        "recompiles_total": recompiles,
        "ledger": {
            "decided": win.closed(),
            "arrival_decision_p50_ms": round(win.percentile(50) or 0.0,
                                             3),
            "arrival_decision_p99_ms": round(win.percentile(99) or 0.0,
                                             3),
        },
        "readback_accounting": acct,
        "readbacks_per_decision": acct["readbacks_per_decision"],
    }
    return out


def main(argv=None):
    # evidence recording only for process-level runs (argv is None →
    # parsing the real command line, i.e. the driver or an operator);
    # programmatic calls pass argv and stay out of the evidence file
    global RECORD_ARGV
    RECORD_ARGV = sys.argv[1:] if argv is None else None
    ap = argparse.ArgumentParser(
        epilog="Output contract: the LAST stdout line is the JSON result. "
               "On a cpu-fallback cfg5 run stdout may carry two JSON lines "
               "(kill-safe primary first, enriched last) — consumers must "
               "take the last line, never json.loads(whole_stdout). Every "
               "emitted line is also appended (with timestamp + git SHA) "
               "to BENCH_DEVICE.jsonl, the committed evidence file.")
    ap.add_argument("--config", default="5",
                    choices=["1", "2", "3", "4", "5", "6", "7",
                             "2p", "3p", "5p"],
                    help="BASELINE config number (default: the 10k pods x "
                         "5k nodes stress config — BASELINE.md's primary "
                         "metric); 2p/3p/5p = predicate-rich variants; "
                         "6/7 = the 50k/100k-node scale axis (two-level "
                         "solve, docs/SCALING.md)")
    # default sized so the primary metric carries >= 5 measured cycles
    # (the first cycle pays jit and is excluded); steady runs are floored
    # at 9 measured cycles (VERDICT r5 directive 9 — p95 on 5 samples is
    # weak), pass a larger --cycles for a soak (60+). None (the parse
    # default) resolves per mode below: 200 for --chaos, 6 otherwise —
    # an EXPLICIT --cycles value is always honored as given.
    ap.add_argument("--cycles", type=int, default=None)
    ap.add_argument("--steady", type=int, default=0, metavar="CHURN_PODS",
                    help="steady-state mode: keep ONE cluster, schedule it "
                         "fully, then churn CHURN_PODS pods per measured "
                         "cycle (whole gangs finish + arrive). Reports "
                         "metric sched_cycle_p50_ms_cfgN_steady.")
    ap.add_argument("--churn-ladder", action="store_true",
                    help="churn ladder (ISSUE 15): ONE persistent cache "
                         "measured at 256/1024/4096 churn pods ascending "
                         "— one JSON line per level, each with an "
                         "'activeset' evidence block (engaged cycles, "
                         "audits, divergences, demotions, active "
                         "tasks/candidate nodes); exits 1 on any "
                         "recompile, audit divergence, demotion, or "
                         ">1 readback per cycle")
    ap.add_argument("--steady-skew", action="store_true",
                    help="with --steady: pin each tick's fresh gangs to "
                         "ONE queue, alternating between the extreme-"
                         "weight queues — sustained cross-queue imbalance "
                         "keeps the reclaim victim path hot (gates "
                         "correctly open). Metric suffix _skew.")
    ap.add_argument("--no-steady-extra", action="store_true",
                    help="skip the steady-state extra measurement the "
                         "default cfg5 run appends to its JSON line")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos soak mode: run --cycles scheduler cycles "
                         "(default 200 when --cycles is left at its "
                         "default) under a seeded randomized fault "
                         "schedule across every seam family and assert "
                         "the robustness invariants (docs/ROBUSTNESS.md);"
                         " reports degraded-mode p50 alongside healthy "
                         "p50. Exit 1 on any invariant violation.")
    ap.add_argument("--chaos-seed", type=int, default=7,
                    help="seed for the chaos fault schedule")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="multi-tenant saturation mode (ISSUE 8): N "
                         "simulated tenants through ONE live sidecar "
                         "pool — first a parity gate (per-tenant "
                         "decisions bit-identical to dedicated "
                         "in-process runs, exit 1 on divergence), then "
                         "the saturation measurement: solves/sec at "
                         "capacity and p99 under 2x offered overload, "
                         "with the shed census. Metric "
                         "tenant_saturation_solves_per_sec.")
    ap.add_argument("--tenant-seconds", type=float, default=3.0,
                    help="per-phase duration for --tenants (capacity "
                         "and overload phases each run this long)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet failover mode (ISSUE 14): N in-process "
                         "sidecars behind the health-weighted tenant "
                         "router, driven at saturation; one sidecar is "
                         "killed abruptly mid-run. Pins: affected "
                         "tenants fail over under a bounded p99 blip, "
                         "unaffected tenants zero shed/zero errors, "
                         "decisions bit-identical to dedicated oracles "
                         "(pre- AND post-kill), standby mega lanes "
                         "bit-identical, recompiles 0. Metric "
                         "fleet_failover_p99_blip_ms; exit 1 on any "
                         "pin.")
    ap.add_argument("--fleet-tenants", type=int, default=4, metavar="N",
                    help="tenant count for --fleet (default 4)")
    ap.add_argument("--fleet-blip-bound-ms", type=float, default=250.0,
                    help="hard bound for the failover p99 blip on the "
                         "--fleet line (stated on the line, enforced)")
    ap.add_argument("--trace-export", default="", metavar="PATH",
                    help="with --steady: write the measured cycles' span "
                         "trees as Chrome trace-event JSON (Perfetto-"
                         "loadable) to PATH and record the path on the "
                         "JSON line (trace_file)")
    ap.add_argument("--pipeline", action="store_true",
                    help="with --chaos: run the soak scheduler on the "
                         "pipelined executor (runtime/pipeline.py) with "
                         "the pipeline.conflict seam armed — the "
                         "consume-time invalidation rung under the full "
                         "invariant bar")
    ap.add_argument("--sustained-churn", type=int, default=256,
                    metavar="CHURN_PODS",
                    help="churn pods per cycle for --mode sustained "
                         "(default 256)")
    ap.add_argument("--trace", default="", metavar="PRESET|PATH",
                    help="drive the run from the workloads/ trace-replay "
                         "plane instead of the synthetic churn templates "
                         "(ISSUE 19): a preset name (borg-diurnal, "
                         "ml-train-heavy) generates a seeded stream "
                         "calibrated to the cluster; a path replays a "
                         "JSONL trace verbatim. Wired through --steady, "
                         "--mode sustained and --mode soak; the soak "
                         "variant arms the backfill-over-reserved "
                         "machinery plus the cache.fold/workload.elastic "
                         "chaos seams and hard-fails on any audit "
                         "divergence or backfill guard counter")
    ap.add_argument("--timeline-dir", default="", metavar="DIR",
                    help="with --mode soak: spill the per-cycle timeline "
                         "digests (obs/timeline.py) to DIR/timeline.jsonl "
                         "— the replayable long-horizon record; empty = "
                         "ring-only (memory stays bounded either way)")
    ap.add_argument("--mode", default="auto",
                    choices=["auto", "batched", "sharded", "hier", "fused",
                             "jax", "host", "rpc", "arrival", "sustained",
                             "soak"],
                    help="allocate engine: auto = size-based selection "
                         "(the shipped default); batched = round-based "
                         "throughput engine (policy-exact, order-"
                         "approximate); fused = bind-for-bind faithful "
                         "scan engine")
    args = ap.parse_args(argv)
    args.config = (int(args.config) if args.config.isdigit()
                   else args.config)
    if args.cycles is None:
        # cfg6/cfg7 cycles are minutes each on a fallback box; 4 total
        # = 3 measured (cycle 0 pays jit and is excluded) banks the
        # scale evidence without eating a sweep window
        args.cycles = (200 if args.chaos
                       else 4 if args.config in (6, 7)
                       # sustained: long enough that in-window arrivals
                       # drain through the saturated backlog and get a
                       # decision inside the measured window
                       else 40 if args.mode == "sustained"
                       # soak: the long-horizon default (ISSUE 17) —
                       # deep enough that the timeline ring wraps and
                       # the drift EWMAs leave their warm-up
                       else 10000 if args.mode == "soak" else 6)

    from kubebatch_tpu import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    backend = ensure_responsive_backend()
    if backend == "cpu-fallback":
        # the watchdog flipped the platform: re-salt the managed cache
        # onto the cpu directory so fallback executables never mix into
        # the accelerator's entries (compilesvc/cache.py cache_salt)
        enable_persistent_compile_cache()

    if args.chaos:
        # the chaos soak evidence line: degraded-mode p50 next to healthy
        # p50, the injected-fault census, and a zero-violation assertion
        # (ISSUE 5; invariants in kubebatch_tpu/sim/chaos.py)
        from kubebatch_tpu.sim.chaos import run_chaos

        rep = run_chaos(cycles=args.cycles, seed=args.chaos_seed,
                        rpc_sidecar=not args.pipeline,
                        pipeline=args.pipeline)
        out = {
            "metric": "chaos_cycle_p50_ms",
            "value": rep.degraded_p50_ms,
            "unit": "ms",
            "vs_baseline": round(rep.healthy_p50_ms
                                 / rep.degraded_p50_ms, 4)
            if rep.degraded_p50_ms else 0.0,
            "healthy_p50_ms": rep.healthy_p50_ms,
            "cycles": rep.cycles,
            "seed": rep.seed,
            "cycle_failures": rep.failures,
            # same int-typed key as the steady lines (tooling scans the
            # JSONL by field name); the per-seam census has its own key
            "faults_injected": sum(rep.faults_injected.values()),
            "faults_by_seam": rep.faults_injected,
            "seam_families": rep.families_injected,
            "max_ladder_level": rep.max_ladder_level,
            "final_ladder_level": rep.final_ladder_level,
            "engines": rep.engines_seen,
            "final_engine": rep.final_engine,
            "recovered_bit_identical": rep.recovered_bit_identical,
            "pods_bound": rep.pods_bound,
            "lease_renew_attempts": rep.lease_renew_attempts,
            "invariant_violations": len(rep.violations),
            "backend": backend,
        }
        if args.pipeline:
            out["metric"] = "chaos_cycle_p50_ms_pipeline"
            out["pipeline_cycles"] = rep.pipeline_cycles
            out["pipeline_conflicts"] = rep.pipeline_conflicts
            out["pipeline_demoted"] = rep.pipeline_demoted
        from kubebatch_tpu.metrics import compile_ms_total, recompiles_total
        out["compile_ms_total"] = round(compile_ms_total(), 1)
        out["recompiles_total"] = recompiles_total()
        if rep.violations:
            out["violations"] = rep.violations[:10]
        emit(out)
        if rep.violations:
            print(f"chaos soak violations: {rep.violations[:10]}",
                  file=sys.stderr)
            return 1
        return 0
    if args.tenants:
        # the multi-tenant saturation line (ISSUE 8): parity gate, then
        # capacity + 2x-overload through one live sidecar. Warm the
        # tenant shape mix FIRST (the "t" config's fused + mega-lane
        # signatures) so the measured window pins recompiles to zero —
        # same enforcement discipline as the steady lines.
        from kubebatch_tpu import compilesvc
        from kubebatch_tpu.metrics import (compile_ms_total,
                                           mega_dispatches_total,
                                           mega_lanes_total,
                                           readback_accounting,
                                           recompiles_total)
        from kubebatch_tpu.sim.tenants import (run_multi_tenant,
                                               run_saturation)

        # ALWAYS in-process here (no ensure_rpc_sidecar probe): every
        # evidence field on this line — mega_dispatches/mega_lanes/
        # shed_modes_seen and the recompiles_total == 0 gate — reads
        # THIS process's counters, so reusing an external daemon would
        # record vacuous zeros while the real work happened elsewhere
        from kubebatch_tpu.rpc.server import make_server
        rpc_server, _port = make_server("127.0.0.1:0")
        rpc_server.start()
        rpc_addr = f"127.0.0.1:{_port}"
        compilesvc.warmup("t")
        r0 = recompiles_total()
        acct0 = readback_accounting()
        parity = run_multi_tenant(n_tenants=args.tenants,
                                  address=rpc_addr)
        sat = run_saturation(n_tenants=args.tenants, address=rpc_addr,
                             duration_s=args.tenant_seconds)
        acct = readback_accounting(since=acct0)
        out = {
            "metric": "tenant_saturation_solves_per_sec",
            "value": sat.capacity_solves_per_sec,
            "unit": "solves/s",
            # vs the north-star cycle budget: one tenant's 1 s period
            # needs 1 solve/s, so N tenants need N — capacity/N is the
            # per-tenant headroom factor
            "vs_baseline": round(sat.capacity_solves_per_sec
                                 / max(1, args.tenants), 4),
            "tenants": args.tenants,
            "parity_bit_identical": parity.bit_identical,
            "parity_cycles": parity.cycles,
            "mega_dispatches": mega_dispatches_total(),
            "mega_lanes": mega_lanes_total(),
            "capacity_p50_ms": sat.capacity_p50_ms,
            "capacity_solves": sat.capacity_solves,
            "overload_offered_per_sec": sat.overload_offered_per_sec,
            "overload_completed_per_sec": sat.overload_completed_per_sec,
            "p99_ms_at_2x": sat.overload_p99_ms,
            "overload_rejected": sat.overload_rejected,
            "overload_stale_served": sat.overload_stale_served,
            "shed_modes_seen": sat.shed_modes_seen,
            "recompiles_total": recompiles_total() - r0,
            "compile_ms_total": round(compile_ms_total(), 1),
            "readback_accounting": acct,
            "readbacks_per_decision": acct["readbacks_per_decision"],
            "backend": backend,
        }
        if parity.mismatched or parity.rpc_errors:
            out["parity_mismatched"] = parity.mismatched
            out["parity_errors"] = parity.rpc_errors[:5]
        emit(out)
        if rpc_server is not None:
            rpc_server.stop(grace=None)
        if not parity.bit_identical:
            print(f"tenant parity FAILED: {parity.mismatched} "
                  f"{parity.rpc_errors}", file=sys.stderr)
            return 1
        if out["recompiles_total"]:
            from kubebatch_tpu.metrics import recompiles_by_reason
            print(f"tenant run recompiled after warm-up: "
                  f"{recompiles_by_reason()}", file=sys.stderr)
            return 1
        return 0

    if args.fleet:
        # the fleet failover line (ISSUE 14): N sidecars at saturation,
        # kill one mid-run, pin the failover cost and the zero-impact
        # guarantees. In-process servers for the same reason as
        # --tenants: every evidence counter reads THIS process.
        from kubebatch_tpu import compilesvc
        from kubebatch_tpu.metrics import recompiles_total
        from kubebatch_tpu.sim.tenants import run_fleet

        compilesvc.warmup("t")
        r0 = recompiles_total()
        rep = run_fleet(n_tenants=args.fleet_tenants,
                        sidecars=args.fleet,
                        duration_s=args.tenant_seconds)
        out = {
            "metric": "fleet_failover_p99_blip_ms",
            "value": rep.failover_p99_blip_ms,
            "unit": "ms",
            # headroom against the stated bound (1.0 = at the bound)
            "vs_baseline": round(rep.failover_p99_blip_ms
                                 / args.fleet_blip_bound_ms, 4),
            "sidecars": rep.sidecars,
            "tenants": rep.tenants,
            "killed_addr": rep.killed_addr,
            "affected_tenants": rep.affected_tenants,
            "failover_p99_blip_bound_ms": args.fleet_blip_bound_ms,
            "pre_kill_p99_ms": rep.pre_kill_p99_ms,
            "post_kill_p99_ms": rep.post_kill_p99_ms,
            "cross_tenant_added_p99_ms": rep.cross_tenant_added_p99_ms,
            "cross_tenant_shed": rep.cross_tenant_shed,
            "cross_tenant_errors": rep.cross_tenant_errors,
            "failovers": rep.failovers,
            "failover_lost": rep.failover_lost,
            "solves_total": rep.solves_total,
            "parity_bit_identical": rep.parity_bit_identical,
            "standby_mega_bit_identical": rep.standby_mega_bit_identical,
            "recompiles_total": recompiles_total() - r0,
            "backend": backend,
        }
        if rep.parity_mismatched or rep.rpc_errors:
            out["parity_mismatched"] = rep.parity_mismatched
            out["parity_errors"] = rep.rpc_errors[:5]
        emit(out)
        failed = []
        if not rep.parity_bit_identical:
            failed.append(f"parity diverged: {rep.parity_mismatched} "
                          f"{rep.rpc_errors[:3]}")
        if not rep.standby_mega_bit_identical:
            failed.append("standby mega lanes diverged from dedicated "
                          "dispatches")
        if out["recompiles_total"]:
            failed.append(f"{out['recompiles_total']} recompiles after "
                          f"warm-up")
        if rep.cross_tenant_shed or rep.cross_tenant_errors:
            failed.append(f"unaffected tenants impacted: "
                          f"shed={rep.cross_tenant_shed} "
                          f"errors={rep.cross_tenant_errors}")
        if rep.failover_lost:
            failed.append(f"{rep.failover_lost} failover(s) refused "
                          f"(standby lagged)")
        if rep.failover_p99_blip_ms > args.fleet_blip_bound_ms:
            failed.append(f"failover blip {rep.failover_p99_blip_ms}ms "
                          f"over the {args.fleet_blip_bound_ms}ms bound")
        for msg in failed:
            print(f"fleet bench: {msg}", file=sys.stderr)
        return 1 if failed else 0

    if args.mode == "sustained":
        # sustained-rate A/B (ISSUE 16): sequential vs pipelined
        # cycles/s on the same box in one process; hard exit-1 pins —
        # any measured-window recompile, any pipeline demotion, or a
        # blocking readback on a conflict-free pipelined window fails
        # the run AFTER the evidence line lands
        out = run_sustained(args.config, max(args.cycles, 9), "auto",
                            churn_pods=args.sustained_churn,
                            trace=args.trace)
        if args.trace:
            out["metric"] += "_trace"
            out["trace_preset"] = args.trace
        out["backend"] = backend
        from kubebatch_tpu.metrics import compile_ms_total
        out["compile_ms_total"] = round(compile_ms_total(), 1)
        emit(out)
        failed = []
        if out["recompiles_total"]:
            failed.append(f"{out['recompiles_total']} recompiles after "
                          f"warm-up")
        if out["pipeline_demotions"]:
            failed.append(f"{out['pipeline_demotions']} pipeline "
                          f"demotion(s) mid-window")
        p = out["pipeline"]
        if not p["pipeline"]["cycles"]:
            failed.append("pipelined arm never committed an overlapped "
                          "cycle")
        if not p["readback_accounting"]["deferred_readbacks"]:
            failed.append("pipelined arm recorded no deferred readbacks "
                          "— the overlap path did not run")
        if not p["pipeline"]["conflicts"] \
                and p["readback_accounting"]["readbacks"]:
            failed.append(
                f"{p['readback_accounting']['readbacks']} BLOCKING "
                f"readbacks on a conflict-free pipelined window (the "
                f"critical-path term must be gone)")
        for msg in failed:
            print(f"sustained bench: {msg}", file=sys.stderr)
        return 1 if failed else 0

    if args.mode == "soak":
        # the long-horizon soak line (ISSUE 17): SLO plane + timeline
        # armed over a multi-thousand-cycle steady regime; the evidence
        # lands FIRST, then any breach / drift / recompile fails the run
        out = run_soak(args.config, max(args.cycles, 128),
                       churn_pods=args.sustained_churn,
                       timeline_dir=args.timeline_dir,
                       trace=args.trace)
        out["backend"] = backend
        from kubebatch_tpu.metrics import compile_ms_total
        out["compile_ms_total"] = round(compile_ms_total(), 1)
        emit(out)
        failed = []
        if out["slo_report"]["breaches_total"]:
            failed.append(
                f"{out['slo_report']['breaches_total']} SLO breach "
                f"window count(s): "
                f"{out['slo_report']['by_objective']}")
        if out["timeline_drift_total"]:
            failed.append(
                f"timeline drift fired {out['timeline_drift_total']} "
                f"time(s): {out['timeline_drift_by_kind']}")
        if out["recompiles_total"]:
            failed.append(f"{out['recompiles_total']} recompiles after "
                          f"warm-up")
        if not out["ledger"]["decided"]:
            failed.append("soak window closed no ledger records — the "
                          "churn regime bound nothing?")
        if args.trace:
            # the trace soak's extra pins (ISSUE 19): audit-clean all
            # the way, guard counters at zero, and the backfill-over-
            # reserved path actually exercised end-to-end
            bf = out["backfill"]
            if out["audit_divergences"]:
                failed.append(f"{out['audit_divergences']} in-soak "
                              f"audit divergence(s) (fold vs full-clone "
                              f"snapshot_diff)")
            if bf["double_binds"] or bf["lost_reservations"]:
                failed.append(
                    f"backfill guard counters nonzero: double_binds="
                    f"{bf['double_binds']} lost_reservations="
                    f"{bf['lost_reservations']}")
            if not bf["over_placements"] or not bf["reclaims"]:
                failed.append(
                    f"trace soak never exercised backfill-over-reserved "
                    f"(over_placements={bf['over_placements']}, "
                    f"reclaims={bf['reclaims']}) — the contention "
                    f"calibration regressed")
            if not out["elastic_events"]:
                failed.append("trace soak saw no elastic grow/shrink "
                              "events")
        for msg in failed:
            print(f"soak bench: {msg}", file=sys.stderr)
        return 1 if failed else 0

    if args.mode == "arrival":
        # schedule-on-arrival mode (ISSUE 9): arrival -> decision
        # p50/p99 through the sub-cycle under steady churn; exit 1 when
        # any offered latency arrival missed its sub-cycle decision
        out = run_arrival(args.config, max(args.cycles, 6),
                          churn_pods=256)
        out["backend"] = backend
        from kubebatch_tpu.metrics import compile_ms_total
        out["compile_ms_total"] = round(compile_ms_total(), 1)
        emit(out)
        if out["arrivals_decided"] < out["arrivals_offered"]:
            print(f"arrival bench: only {out['arrivals_decided']} of "
                  f"{out['arrivals_offered']} latency arrivals got a "
                  f"sub-cycle decision", file=sys.stderr)
            return 1
        if out["recompiles_total"]:
            print(f"arrival bench: {out['recompiles_total']} measured-"
                  f"window recompiles (sub-cycle shapes must ride the "
                  f"registered buckets)", file=sys.stderr)
            return 1
        return 0

    if args.churn_ladder:
        # the active-set ladder (ISSUE 15): per-level lines with hard
        # exit-1 pins — any recompile, audit divergence, demotion, or
        # second readback on a measured cycle fails the run AFTER the
        # evidence lines land (the jsonl still records what happened)
        rows = run_churn_ladder(args.config, max(args.cycles, 9),
                                args.mode)
        from kubebatch_tpu.metrics import compile_ms_total
        failed = []
        for row in rows:
            lat = row["latencies"]
            lvl = row["churn_pods"]
            seconds = sum(lat)
            p50 = float(np.percentile(lat, 50) * 1e3)
            rb = round(float(np.mean(row["readbacks"])), 1) \
                if row["readbacks"] else 0.0
            line = {
                "metric": (f"sched_cycle_p50_ms_cfg{args.config}"
                           f"_churn{lvl}"),
                "value": round(p50, 3),
                "unit": "ms",
                "p95_ms": round(float(np.percentile(lat, 95) * 1e3), 3),
                "max_ms": round(float(np.max(lat) * 1e3), 3),
                "churn_pods": lvl,
                "measured_cycles": len(lat),
                "pods_bound_per_sec": round(row["bound"] / seconds, 1)
                if seconds else 0.0,
                "action_ms": row["action_ms"],
                "engines": sorted(set(row["engines"])),
                "readbacks_per_cycle": rb,
                "recompiles_total": row["recompiles"],
                "activeset": row["activeset"],
                "mode": args.mode,
                "backend": backend,
                "compile_ms_total": round(compile_ms_total(), 1),
            }
            emit(line)
            a = row["activeset"]
            if row["recompiles"]:
                failed.append(f"churn {lvl}: {row['recompiles']} "
                              f"recompiles after warm-up")
            if a["divergences"]:
                failed.append(f"churn {lvl}: {a['divergences']} audit "
                              f"divergences (active set must be "
                              f"bit-identical to full width)")
            if a["demotions"]:
                failed.append(f"churn {lvl}: {a['demotions']} activeset "
                              f"demotions")
            if rb > 1.0:
                failed.append(f"churn {lvl}: {rb} readbacks/cycle "
                              f"(budget is ONE)")
        for msg in failed:
            print(f"churn ladder: {msg}", file=sys.stderr)
        return 1 if failed else 0

    rpc_addr, rpc_server = "", None
    if args.mode == "rpc":
        # the rpc deployment-mode bench (VERDICT r5 weak 4): solve
        # through a LIVE sidecar, record cycle p50 plus the per-dispatch
        # hop cost, and assert zero fallback engagements — a fallback
        # would silently measure the in-process engine instead
        rpc_addr, rpc_server = ensure_rpc_sidecar()
        from kubebatch_tpu.rpc import client as rpc_client
        rpc_client.DISPATCH_STATS.clear()
    if backend == "cpu-fallback" and not args.steady:
        # run the REQUESTED config on the host XLA backend so the degraded
        # number still measures the full stack at the asked-for scale (a
        # cfg5 cycle is ~2.8 s on CPU vs ~0.35 s through the tunnel);
        # keep >=5 measured cycles when asked for them — the whole run is
        # ~25 s with the persistent compile cache — and label the backend
        # honestly
        args.cycles = min(args.cycles, 6)

    if args.steady > 0:
        # >=9 measured cycles so the reported p95 means something
        (latencies, bound, action_ms, readbacks, rss_mb, engines,
         recompiles, span_counts, trace_roots, phase_ms,
         acct) = run_steady(
            args.config, max(args.cycles, 9), args.mode, args.steady,
            skew=args.steady_skew, trace=args.trace)
        p50_ms = float(np.percentile(latencies, 50) * 1e3)
        seconds = sum(latencies)
        suffix = "_steady_skew" if args.steady_skew else "_steady"
        if args.trace:
            suffix += "_trace"
        out = {
            "metric": f"sched_cycle_p50_ms_cfg{args.config}{suffix}",
            "value": round(p50_ms, 3),
            "unit": "ms",
            "vs_baseline": round(15.0 / p50_ms, 4) if p50_ms else 0.0,
            "p95_ms": round(float(np.percentile(latencies, 95) * 1e3), 3),
            "max_ms": round(float(np.max(latencies) * 1e3), 3),
            "rss_peak_mb": round(rss_mb, 1),
            "pods_bound_per_sec": round(bound / seconds, 1) if seconds
            else 0.0,
            "churn_pods": args.steady,
            "measured_cycles": len(latencies),
            "action_ms": action_ms,
            "mode": args.mode,
            "readbacks_per_cycle": round(float(np.mean(readbacks)), 1)
            if readbacks else 0.0,
            # readbacks per unit of scheduling work over the measured
            # window (metrics.readback_accounting; decisions come from
            # the device telemetry frames' bound counts)
            "readback_accounting": acct,
            "readbacks_per_decision": acct["readbacks_per_decision"],
            "engines": sorted(set(engines)),
            # the steady host split off the update_host_phase keys
            # (ISSUE 9): host_share_ms keeps its historical definition
            # (tensorize + replay + close); host_share_split names the
            # new-path phases — fold (event-folded snapshot assembly),
            # apply (bind_many column ops, nested inside replay), audit
            # (lazy full-clone diff; 0.0 unless a cadence is armed)
            "host_phase_ms": phase_ms,
            "host_share_ms": round(phase_ms.get("tensorize", 0.0)
                                   + phase_ms.get("replay", 0.0)
                                   + phase_ms.get("close", 0.0), 3),
            "host_share_split": {
                "fold": phase_ms.get("fold", 0.0),
                "apply": phase_ms.get("apply", 0.0),
                "audit": phase_ms.get("audit", 0.0)},
            "backend": backend,
        }
        if args.trace:
            out["trace_preset"] = args.trace
        # injection disarmed -> these pin to zero; a nonzero value on a
        # steady line means a seam fired outside an armed plan
        from kubebatch_tpu.metrics import (compile_ms_total,
                                           cycle_failures_total,
                                           fault_injected_total)
        out["faults_injected"] = sum(fault_injected_total().values())
        out["cycle_failures"] = cycle_failures_total()
        # the recompiles==0 invariant (ISSUE 6): the in-run warm-up
        # cycles traced every steady shape, so a compile inside the
        # measured window is a structural failure, not wall-time noise
        out["recompiles_total"] = recompiles
        out["compile_ms_total"] = round(compile_ms_total(), 1)
        if args.config in (6, 7):
            # the scale-axis steady line carries the same downsampled
            # decision evidence as the cold line (ISSUE 10 done-bar)
            try:
                out.update(downsampled_oracle_check(args.config))
            except Exception as e:   # pragma: no cover — diagnostics
                out["oracle_error"] = f"{type(e).__name__}: {e}"
        # the cost of always-on tracing, on the record next to the wall
        # numbers (ISSUE 7): span count per measured cycle and the
        # calibrated per-span cost x that count — an estimate labeled as
        # such (self-measuring every span would double the timestamps)
        from kubebatch_tpu import obs as _obs
        if span_counts:
            spc = float(np.mean(span_counts))
            out["spans_per_cycle"] = round(spc, 1)
            out["trace_overhead_ms"] = round(
                spc * _obs.span_overhead_estimate() * 1e3, 4)
        if args.trace_export and trace_roots:
            from kubebatch_tpu.obs import export as _obs_export
            out["trace_file"] = _obs_export.write_trace(
                args.trace_export, trace_roots)
        if args.mode == "rpc":
            # same hop-cost / zero-fallback contract as the cold path: a
            # steady rpc line must not silently record in-process cycles
            out.update(rpc_stats_fields(engines, rpc_addr))
        emit(out)
        if rpc_server is not None:
            rpc_server.stop(grace=None)
        if out.get("rpc_fallbacks"):
            print(f"rpc bench engaged fallback engines: {engines}",
                  file=sys.stderr)
            return 1
        if recompiles:
            from kubebatch_tpu.metrics import recompiles_by_reason
            print(f"steady run recompiled after warm-up: "
                  f"{recompiles_by_reason()}", file=sys.stderr)
            return 1
        return 0

    (latencies, bound, seconds, evicted, action_ms, engines,
     readbacks, kernel_s, phase_ms, cold_split, acct) = run_config(
        args.config, args.cycles, args.mode)
    p50_ms = float(np.percentile(latencies, 50) * 1e3)
    p95_ms = float(np.percentile(latencies, 95) * 1e3)
    pods_per_sec = bound / seconds if seconds > 0 else 0.0
    north_star_ms = 15.0
    out = {
        "metric": f"sched_cycle_p50_ms_cfg{args.config}",
        "value": round(p50_ms, 3),
        "unit": "ms",
        "vs_baseline": round(north_star_ms / p50_ms, 4) if p50_ms else 0.0,
        "p95_ms": round(p95_ms, 3),
        "pods_bound_per_sec": round(pods_per_sec, 1),
        "pods_bound_per_cycle": bound // max(1, len(latencies)),
        "measured_cycles": len(latencies),
        "action_ms": action_ms,
        "mode": args.mode,
        "engines": sorted(set(engines)),
        # blocking device->host transfers per measured cycle — the
        # environment-sensitive cost driver (each one pays the tunnel
        # RTT); budget pinned by tests/test_readbacks.py
        "readbacks_per_cycle": round(float(np.mean(readbacks)), 1)
        if readbacks else 0.0,
        "readbacks_max": max(readbacks) if readbacks else 0,
        "readback_accounting": acct,
        "readbacks_per_decision": acct["readbacks_per_decision"],
        # solver dispatch wall (incl. the blocking-read RTTs): the cold
        # split is kernel ~= this - readbacks x link RTT
        "solver_dispatch_ms_per_cycle": round(
            1e3 * float(np.mean(kernel_s)), 1) if kernel_s else 0.0,
        # cold host split per cycle (median ms from the committed phase
        # counters): open / tensorize / replay / close; host_share_ms =
        # tensorize + replay + close — the VERDICT r5 directive-1 metric
        # (device solve and its blocking readback excluded)
        "host_phase_ms": phase_ms,
        "host_share_ms": round(phase_ms.get("tensorize", 0.0)
                               + phase_ms.get("replay", 0.0)
                               + phase_ms.get("close", 0.0), 3),
        # first-cycle split (cold_compile_ms vs cold_host_ms — the jit
        # compile no longer hides inside the host share) + the compile
        # manager's process counters, on every line (docs/COMPILE.md)
        **cold_split,
        "backend": backend,
    }
    from kubebatch_tpu.metrics import compile_ms_total, recompiles_total

    def stamp_compile_counters():
        """(Re)stamp the compile-manager process counters — called again
        right before the FINAL emit so the authoritative last line covers
        whatever the steady extra compiled (consumers parse the last
        line; stale counters on it would under-report the compile wall)."""
        out["compile_ms_total"] = round(compile_ms_total(), 1)
        out["recompiles_total"] = recompiles_total()

    stamp_compile_counters()
    if args.config in (6, 7):
        # the scale-axis done-bar's decision evidence (ISSUE 10): the
        # two-level solve vs the host oracle + the flat engine on a
        # downsampled twin of this spec, fields on the same line. The
        # check's OWN downsampled graphs compile after the warm mark —
        # attributed separately so recompiles_total keeps meaning "the
        # production cycles", which cycle 0 warmed (see run_config)
        rc_cycles = recompiles_total()
        try:
            out.update(downsampled_oracle_check(args.config))
        except Exception as e:   # pragma: no cover — diagnostics only
            out["oracle_error"] = f"{type(e).__name__}: {e}"
        out["oracle_check_compiles"] = recompiles_total() - rc_cycles
        out["recompiles_total"] = rc_cycles
    if evicted:
        out["evictions_per_cycle"] = evicted // max(1, len(latencies))
    #: every cycle the rpc evidence fields must cover — the cfg5
    #: steady-extra below appends its cycles so dispatch/hop counts and
    #: the fallback count describe the SAME set (an internally
    #: inconsistent evidence line is worse than none)
    rpc_cycle_engines = list(engines)
    # the primary cfg5 line also carries a steady-state measurement (the
    # regime the 1 s schedule loop actually lives in); guarded so a steady
    # failure can never cost the primary number. On cpu-fallback the extra
    # is attempted too (the compile cache is warm from the primary run and
    # a steady cycle is ~0.1 s there since the reclaim provably-idle
    # gates), UNLESS the primary p50 shows a pathologically slow host —
    # then the old timeout concern stands and the extra is skipped.
    if args.config == 5 and not args.no_steady_extra \
            and (backend != "cpu-fallback" or out["value"] < 5000):
        if backend == "cpu-fallback":
            # the extra's warmup re-schedules a fresh cluster at full
            # CPU rate (~10-20 s); if a driver timeout kills us mid-way
            # the primary number must already be on stdout — consumers
            # taking the LAST line get the enriched one when it lands.
            # NOTE stdout may then carry TWO JSON lines (primary first,
            # enriched last): consumers must parse the LAST line (see
            # --help epilog and README "Benchmarks").
            emit(out, flush=True, partial=True)
        try:
            churn = 256
            (s_lat, s_bound, s_act, s_rb, _, s_eng, s_rc, s_spans,
             _s_roots, s_phase, s_acct) = run_steady(args.config, 9,
                                                     args.mode, churn)
            out["steady_recompiles"] = s_rc
            out["steady_readbacks_per_decision"] = \
                s_acct["readbacks_per_decision"]
            out["steady_host_phase_ms"] = s_phase
            out["steady_p50_ms"] = round(
                float(np.percentile(s_lat, 50) * 1e3), 3)
            out["steady_p95_ms"] = round(
                float(np.percentile(s_lat, 95) * 1e3), 3)
            out["steady_churn_pods"] = churn
            out["steady_measured_cycles"] = len(s_lat)
            out["steady_action_ms"] = s_act
            out["steady_readbacks_per_cycle"] = round(
                float(np.mean(s_rb)), 1) if s_rb else 0.0
            if s_spans:
                from kubebatch_tpu import obs as _obs
                spc = float(np.mean(s_spans))
                out["steady_spans_per_cycle"] = round(spc, 1)
                out["steady_trace_overhead_ms"] = round(
                    spc * _obs.span_overhead_estimate() * 1e3, 4)
            if args.mode == "rpc":
                # the steady-extra's cycles are rpc evidence too — a
                # breaker trip mid-extra must not record in-process
                # steady numbers under an rpc line with exit 0
                out["steady_engines"] = sorted(set(s_eng))
            rpc_cycle_engines += s_eng
        except Exception as e:   # pragma: no cover — diagnostics only
            out["steady_error"] = f"{type(e).__name__}: {e}"
    if args.mode == "rpc":
        # zero-fallback assertion rides the shared fields (computed
        # AFTER the steady extra so dispatches, hop cost and fallbacks
        # all describe every cycle on this line); a nonzero count fails
        # the run after the line is emitted so the evidence file still
        # records what happened
        out.update(rpc_stats_fields(rpc_cycle_engines, rpc_addr))
    if args.config not in (6, 7):
        # cover the steady extra's compiles too; cfg6/7 already split
        # cycle recompiles from the oracle check's (above)
        stamp_compile_counters()
    emit(out)
    if rpc_server is not None:
        rpc_server.stop(grace=None)
    if out.get("rpc_fallbacks"):
        print(f"rpc bench engaged fallback engines: {engines}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
